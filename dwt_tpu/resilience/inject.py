"""Deterministic fault injection for the resilience subsystem.

Production training must survive four failure classes that are impossible
to reproduce on demand with real hardware: numeric divergence (a NaN loss
at some step), a preemption/crash landing *inside* a checkpoint save, a
checkpoint truncated by a dead filesystem, and a corrupt/undecodable
dataset item.  This module provides deterministic stand-ins for each,
consulted by the production code at exactly the points the real fault
would strike:

* ``maybe_nan(state, metrics, lo, hi)`` — called by the train loops after
  each dispatch; poisons params + metrics with NaN once, when the armed
  step falls in ``[lo, hi]`` (the divergence-guard recovery paths).
* ``maybe_crash_mid_save(step)`` — called by ``save_state`` after the
  checkpoint bytes are written but *before* the atomic finalize rename;
  raises :class:`SimulatedCrash`, leaving an unfinalized tmp directory
  behind exactly like a SIGKILL mid-save (the restore-fallback path).
* :class:`FlakyDataset` — wraps any dataset so chosen indices raise for
  the first N accesses (transient I/O) or always (corrupt item), driving
  the loader's retry/quarantine path.

All hooks are no-ops (one ``is None`` check) unless a plan is armed, so
the production hot paths pay nothing.  Arm programmatically with
:func:`arm`, or via the ``DWT_FAULT_PLAN`` env var (JSON, read once at
first use) for subprocess tests.  Every fault fires at most once per arm:
recovery paths must not re-trip on the state they just repaired.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, Optional, Tuple

ENV_VAR = "DWT_FAULT_PLAN"


class SimulatedCrash(Exception):
    """Raised by an armed kill-mid-save hook (stands in for SIGKILL)."""


@dataclasses.dataclass
class FaultPlan:
    """One-shot fault schedule.  Fields default to "never fire"."""

    # Poison params/metrics with NaN after the train step with this
    # (1-based) global step number completes.
    nan_at_step: Optional[int] = None
    # Raise SimulatedCrash inside save_state after the bytes are written
    # but before the finalize rename.  True = next save; int = the save
    # at that step.
    crash_in_save: Any = None

    @classmethod
    def from_env(cls) -> Optional["FaultPlan"]:
        raw = os.environ.get(ENV_VAR)
        if not raw:
            return None
        spec = json.loads(raw)
        return cls(
            nan_at_step=spec.get("nan_at_step"),
            crash_in_save=spec.get("crash_in_save"),
        )


_plan: Optional[FaultPlan] = None
_env_checked = False


def arm(plan: FaultPlan) -> None:
    global _plan, _env_checked
    _plan = plan
    _env_checked = True


def disarm() -> None:
    global _plan, _env_checked
    _plan = None
    # Re-reading the env on the next current() would re-arm a consumed
    # subprocess plan — mark it checked so disarm is final in-process.
    _env_checked = True


def current() -> Optional[FaultPlan]:
    """The armed plan, lazily picking up ``DWT_FAULT_PLAN`` once."""
    global _plan, _env_checked
    if not _env_checked:
        _env_checked = True
        _plan = FaultPlan.from_env()
    return _plan


def _poison_tree(tree: Any) -> Any:
    import jax
    import jax.numpy as jnp

    def nan_like(x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
            return x * jnp.asarray(jnp.nan, x.dtype)
        return x

    return jax.tree.map(nan_like, tree)


def maybe_nan(state, metrics, lo: int, hi: Optional[int] = None) -> Tuple[Any, Any]:
    """Poison ``(state.params, metrics)`` with NaN if the armed step is in
    ``[lo, hi]`` (both inclusive; ``hi`` defaults to ``lo``).  Fires once.

    The chunked (``steps_per_dispatch``) path passes the whole dispatched
    step range, since the host only regains control at chunk boundaries —
    the same granularity at which a real mid-chunk NaN becomes observable.
    """
    plan = current()
    if plan is None or plan.nan_at_step is None:
        return state, metrics
    hi = lo if hi is None else hi
    if not (lo <= plan.nan_at_step <= hi):
        return state, metrics
    plan.nan_at_step = None  # one-shot
    state = state.replace(params=_poison_tree(state.params))
    return state, _poison_tree(dict(metrics))


def maybe_crash_mid_save(step: int) -> None:
    """Raise :class:`SimulatedCrash` if armed for this save.  Fires once."""
    plan = current()
    if plan is None or plan.crash_in_save is None:
        return
    if plan.crash_in_save is True or int(plan.crash_in_save) == int(step):
        plan.crash_in_save = None  # one-shot
        raise SimulatedCrash(f"injected crash during checkpoint save @{step}")


class FlakyDataset:
    """Dataset wrapper whose chosen indices raise on access.

    ``fail={idx: n}`` — index ``idx`` raises :class:`OSError` for its
    first ``n`` accesses, then succeeds (transient I/O; exercises retry).
    ``corrupt=(idx, ...)`` — those indices always raise (undecodable item;
    exercises quarantine).  Deterministic: failures depend only on the
    access count per index.
    """

    def __init__(self, base, fail: Optional[Dict[int, int]] = None,
                 corrupt: Tuple[int, ...] = ()):
        self.base = base
        self.fail = dict(fail or {})
        self.corrupt = frozenset(corrupt)
        self._counts: Dict[int, int] = {}

    def __len__(self) -> int:
        return len(self.base)

    def __getitem__(self, i: int):
        i = int(i)
        if i in self.corrupt:
            raise OSError(f"injected corrupt item {i}")
        seen = self._counts.get(i, 0)
        self._counts[i] = seen + 1
        if seen < self.fail.get(i, 0):
            raise OSError(f"injected transient failure {i} (attempt {seen + 1})")
        return self.base[i]
