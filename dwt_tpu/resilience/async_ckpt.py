"""Asynchronous checkpoint pipeline: snapshot → digest → write, off the
hot path.

``utils.checkpoint.save_state`` is safe (atomic finalize, manifest digest,
newest-valid fallback) but synchronous: the train loop stalls for a
device→host fetch, a full SHA-256 over the param tree, and an Orbax
serialize + fsync + rename before the next step can dispatch.  At the
flagship's ~8-95 ms/step that multi-second stall at ``ckpt_every`` cadence
is a pure throughput tax that grows with model size.

:class:`AsyncCheckpointer` splits a save into a cheap hot-path half and a
background half:

* **hot path** — :meth:`save` deep-copies the state into fresh
  *non-donated* device buffers (``jnp.copy`` per leaf: dispatch only, no
  host sync — the runtime orders the copy before any later donation of the
  source buffers) and enqueues the task.  The loop dispatches its next
  step immediately.
* **writer thread** — runs the existing ``save_state`` wholesale: finite
  gate, Orbax write, SHA-256 manifest, atomic rename, prune, and (multi-
  host) the process-0-finalize + cross-process barrier.  Reusing the
  primitive keeps the on-disk format byte-identical to a synchronous save,
  so every restore/fallback path is unchanged.

Correctness rules the train loops must follow (and do — ``train/loop.py``):

* **single in-flight** — a second :meth:`save` arriving while one is
  running joins it first (backpressure), never queues unboundedly.
* **rendezvous** — :meth:`flush` joins the in-flight save; required before
  anything that must observe the checkpoint durably on disk: preemption
  save-and-exit, the final save, guard rollback/restore (the newest valid
  checkpoint must include the in-flight one, and the writer must not race
  the restore's directory walk), and best-record updates (``best.json``
  must never point at an artifact that does not exist yet).
* **errors surface, never vanish** — a writer exception is re-raised on
  the next :meth:`save`/:meth:`flush` (the failed save is logged; the new
  save is *not* silently dropped — the caller sees the failure exactly
  like a synchronous save raising).

Multi-host: NOT supported — the writer thread dispatches device work
(the finite-gate jit, ``save_state``'s cross-process barrier) whose
launch order relative to the main thread's train-step collectives is
thread-scheduling dependent, and multi-host JAX requires an identical
collective launch order on every process (mismatch = runtime deadlock).
The train loops therefore downgrade ``--async_ckpt`` to the synchronous
save path when ``jax.process_count() > 1``; a collective-free writer
(host-side snapshot, pure-I/O task) is the future lift for multi-host.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Optional

import jax
import jax.numpy as jnp

log = logging.getLogger(__name__)


# One compiled whole-tree copy, not per-leaf eager jnp.copy: eager dispatch
# of ~75 small ops contends with a busy compute queue (measured: the
# per-leaf form stalls 15→170 ms as the dispatch queue deepens; the jitted
# form stays ~1 ms).  jit never donates by default, so the outputs are
# fresh buffers, and it follows the inputs' shardings on DP/multi-host
# states.  Cached per (structure, shapes) by jit itself.
_snapshot_fn = None


def snapshot_state(state: Any) -> Any:
    """Deep-copy ``state`` into fresh non-donated device buffers.

    Dispatch-only: no host transfer, no sync.  The copy must happen on the
    enqueueing thread — JAX orders it before any later donation of the
    source buffers by the next train step, which a copy issued from the
    writer thread could race.
    """
    global _snapshot_fn
    if _snapshot_fn is None:
        _snapshot_fn = jax.jit(lambda s: jax.tree.map(jnp.copy, s))
    return _snapshot_fn(state)


class AsyncCheckpointer:
    """Single-in-flight background checkpoint writer (see module doc).

    Thread model: at most one writer thread alive at a time; ``save``
    joins any previous writer before starting the next (backpressure).
    All public methods are main-thread only — the loops drive saves from
    one thread, so no internal locking is needed beyond the join.
    """

    def __init__(self):
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._error_step: Optional[int] = None
        self._last_path: Optional[str] = None
        self._pending_step: Optional[int] = None

    # ------------------------------------------------------------- internals

    def _run(self, targets, step: int, snapshot: Any) -> None:
        # Deferred import: utils.checkpoint imports resilience.inject, so a
        # module-level import here would be circular via the package init.
        from dwt_tpu.utils.checkpoint import save_state

        try:
            for ckpt_dir, kwargs in targets:
                path = save_state(ckpt_dir, step, snapshot, **kwargs)
                if path is not None:  # None = refused (non-finite), no artifact
                    self._last_path = path
        except BaseException as e:  # surfaced on the next enqueue/flush
            self._error = e
            self._error_step = step
            log.warning("async checkpoint save @%d failed: %s", step, e)

    def _join(self) -> None:
        t = self._thread
        if t is not None:
            t.join()
            self._thread = None
            self._pending_step = None

    def _raise_pending(self) -> None:
        if self._error is not None:
            e, step = self._error, self._error_step
            self._error = self._error_step = None
            log.error("surfacing failed async checkpoint save @%s", step)
            raise e

    # ------------------------------------------------------------------ API

    @property
    def in_flight(self) -> Optional[int]:
        """Step of the save currently being written, or None."""
        return self._pending_step

    def save(self, ckpt_dir: str, step: int, state: Any, **kwargs) -> None:
        """Snapshot ``state`` and enqueue its save; returns immediately
        unless a previous save is still in flight (backpressure join).

        ``kwargs`` pass through to ``save_state`` (``keep=``,
        ``require_finite=``).  A previous writer failure is raised HERE,
        before the new save is enqueued, so no failure is ever swallowed
        between rendezvous points.
        """
        self.save_multi([(ckpt_dir, kwargs)], step, state)

    def save_multi(self, targets, step: int, state: Any) -> None:
        """One snapshot, several directory writes in a single writer task.

        ``targets`` is ``[(ckpt_dir, save_state_kwargs), ...]``.  A
        coinciding cadence boundary (periodic save + its same-step anchor)
        must cost the hot path ONE enqueue — two sequential ``save`` calls
        would make the second's backpressure join block the loop for the
        first save's full writer duration, reintroducing the sync stall on
        exactly those steps.
        """
        self._join()
        self._raise_pending()
        snapshot = snapshot_state(state)
        self._pending_step = int(step)
        self._thread = threading.Thread(
            target=self._run,
            args=(list(targets), int(step), snapshot),
            name=f"dwt-ckpt-writer-{int(step)}",
            daemon=True,
        )
        self._thread.start()

    def flush(self) -> Optional[str]:
        """Join the in-flight save (if any); raise its error if it failed.

        Returns the path of the most recent successfully finalized
        checkpoint (None if no save has completed yet).
        """
        self._join()
        self._raise_pending()
        return self._last_path

    def close(self, raise_errors: bool = True) -> None:
        """Final rendezvous.  ``raise_errors=False`` is for abnormal-exit
        cleanup paths where a writer error must not mask the original
        exception (it is still logged by the writer)."""
        if raise_errors:
            self.flush()
            return
        self._join()
        self._error = self._error_step = None
