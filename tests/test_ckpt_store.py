"""Content-addressed delta checkpoint store tests (ISSUE-13).

Contract groups, mirroring the subsystem's consumers:

* **delta mechanics** — a save writes only the leaves whose digest
  moved; the chain cap forces periodic full saves; a structure change
  forces a full save; restore resolves through the chain bitwise.
* **validity + fallback** — a torn chain (missing parent blob, pruned
  parent manifest, unpromoted stage) makes the candidate invalid with
  the skip reason logged, and the newest-valid walk falls back to the
  last restorable save — never a torn or mixed-generation restore.
* **GC refcount matrix** — pruning is chain-aware (a kept/anchor/best
  manifest's ancestors survive), orphaned blobs are swept, and every
  surviving checkpoint still restores bitwise afterwards.
* **cross-format / cross-topology** — delta and whole-tree saves mix in
  one directory; a delta checkpoint saved under one plan restores under
  a model-sharded plan (restore-to-spec: leaves LAND sharded, memmap'd
  blobs sliced per shard) and across mesh shapes with per-leaf parity.
* **consumers** — the fleet watcher emits delta candidates on the
  unchanged (step, digest) dedup key; the serving engine loads a delta
  checkpoint through the same ranked walk; heartbeat records surface
  the bytes-written counter.

The 1→2-process topology-elastic resume E2E is slow-marked (spawns
coordinated OS processes); everything else is tier-1.
"""

import functools
import json
import os
import shutil
import socket
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dwt_tpu.ckpt import (
    blob_store_root,
    cas_invalid_reason,
    gc_blobs,
    promote_delta,
    save_delta,
    stage_delta,
    tree_bytes,
)
from dwt_tpu.ckpt.store import _blob_path, resolve_leaves
from dwt_tpu.nn import LeNetDWT
from dwt_tpu.resilience import inject
from dwt_tpu.resilience.inject import FaultPlan
from dwt_tpu.train import adam_l2, create_train_state
from dwt_tpu.utils.checkpoint import (
    anchor_dir,
    checkpoint_invalid_reason,
    host_fetch,
    params_digest,
    prune_checkpoints,
    restore_newest,
    restore_state,
    restore_tree,
    save_state,
    valid_steps,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _disarm_faults():
    yield
    inject.disarm()


def _tree(seed=0, extra=None):
    """A small host pytree standing in for a TrainState: 'params' leaves
    (digested) plus moment-ish ballast.  Cheap — most store contracts
    need no real model."""
    rng = np.random.default_rng(seed)
    tree = {
        "params": {
            "backbone": {"kernel": rng.normal(size=(64, 32)).astype(np.float32)},
            "head": {"kernel": rng.normal(size=(8, 4)).astype(np.float32)},
        },
        "mu": {"backbone": np.zeros((64, 32), np.float32)},
        "step": np.asarray(0, np.int32),
    }
    if extra:
        tree[extra] = np.ones((3,), np.float32)
    return tree


def _churn(tree, step, keys=("head",)):
    """Perturb only ``keys``' param leaves (+ the step counter)."""
    out = json.loads("{}")  # fresh dict
    out = {
        "params": {
            k: (
                {"kernel": v["kernel"] * 1.01}
                if k in keys else {"kernel": v["kernel"]}
            )
            for k, v in tree["params"].items()
        },
        "mu": dict(tree["mu"]),
        "step": np.asarray(step, np.int32),
    }
    return out


def _manifest(d, step):
    with open(os.path.join(d, str(step), "manifest.json")) as f:
        return json.load(f)


def _assert_tree_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@functools.lru_cache(maxsize=1)
def _lenet_state():
    model = LeNetDWT(group_size=4)
    tx = adam_l2(1e-3)
    sample = jnp.zeros((2, 4, 28, 28, 1), jnp.float32)
    return model, create_train_state(model, jax.random.key(0), sample, tx)


# ---------------------------------------------------------- delta mechanics


def test_delta_save_writes_only_moved_leaves(tmp_path):
    d = str(tmp_path / "ck")
    t1 = _tree()
    save_delta(d, 1, t1)
    m1 = _manifest(d, 1)
    assert m1["mode"] == "full" and m1["parent_step"] is None
    assert len(m1["leaves"]) == m1["leaf_count"] == 4

    t2 = _churn(t1, 2)
    save_delta(d, 2, t2)
    m2 = _manifest(d, 2)
    assert m2["mode"] == "delta" and m2["parent_step"] == 1
    # Only head kernel + step moved; the delta manifest records exactly
    # those (the manifest diff reuses the content-addressing digests).
    assert sorted(e["path"] for e in m2["leaves"]) == [
        "['params']['head']['kernel']", "['step']",
    ]
    assert m2["bytes_written"] < m1["bytes_written"] / 5
    assert valid_steps(d) == [1, 2]
    _assert_tree_equal(restore_state(d, t1), t2)
    _assert_tree_equal(restore_state(d, t1, step=1), t1)


def test_chain_cap_forces_periodic_full_saves(tmp_path):
    d = str(tmp_path / "ck")
    t = _tree()
    modes = []
    for s in range(1, 8):
        t = _churn(t, s)
        save_delta(d, s, t, delta_max_chain=2)
        modes.append(_manifest(d, s)["mode"])
    # depth cap 2: full, d1, d2, full, d1, d2, full
    assert modes == ["full", "delta", "delta", "full", "delta", "delta",
                     "full"]
    _assert_tree_equal(restore_state(d, t), t)


def test_structure_change_forces_full_save(tmp_path):
    d = str(tmp_path / "ck")
    save_delta(d, 1, _tree())
    save_delta(d, 2, _tree(seed=1, extra="swbn"))  # new leaf: no chain
    assert _manifest(d, 2)["mode"] == "full"


def test_mixed_formats_one_directory(tmp_path):
    """delta <-> full cross-restore: a classic whole-tree save and delta
    saves coexist in one ckpt_dir; a delta cannot chain onto a classic
    parent (forced full) and the walk restores every step correctly."""
    _, state = _lenet_state()
    d = str(tmp_path / "ck")
    save_state(d, 1, state)  # classic Orbax
    host2 = host_fetch(state.replace(step=state.step + 1))
    save_delta(d, 2, host2)
    assert _manifest(d, 2)["mode"] == "full"  # classic parent: no chain
    host3 = host_fetch(state.replace(step=state.step + 2))
    save_delta(d, 3, host3)
    assert _manifest(d, 3)["mode"] == "delta"
    assert valid_steps(d) == [1, 2, 3]
    assert int(restore_state(d, state).step) == int(state.step) + 2
    assert int(restore_state(d, state, step=1).step) == int(state.step)
    # and classic again on top of deltas
    save_state(d, 4, state.replace(step=state.step + 3))
    assert int(restore_state(d, state).step) == int(state.step) + 3


# ----------------------------------------------------- validity + fallback


def test_missing_parent_blob_invalidates_chain(tmp_path, caplog):
    d = str(tmp_path / "ck")
    t1 = _tree()
    save_delta(d, 1, t1)
    t2 = _churn(t1, 2, keys=("backbone",))
    save_delta(d, 2, t2)
    t3 = _churn(t2, 3, keys=("head",))
    save_delta(d, 3, t3)  # inherits backbone blob from the delta at 2

    # Tear the chain: the blob the delta at step 2 wrote vanishes.
    resolved = resolve_leaves(os.path.join(d, "2"))
    entry, store = resolved.entries["['params']['backbone']['kernel']"]
    os.remove(_blob_path(store, entry["digest"]))

    with caplog.at_level("WARNING", logger="dwt_tpu.utils.checkpoint"):
        steps = valid_steps(d)
    assert steps == [1]  # 2 AND 3 fall: both resolve through that blob
    assert any("missing blob" in r.message for r in caplog.records)
    reason = checkpoint_invalid_reason(os.path.join(d, "3"))
    assert reason is not None and "blob" in reason
    # Fallback lands on the last FULL save, bitwise — never a mix.
    restored, src = restore_newest(d, t1)
    assert src == "checkpoint"
    _assert_tree_equal(restored, t1)


def test_missing_parent_manifest_invalidates_descendants(tmp_path):
    d = str(tmp_path / "ck")
    t = _tree()
    save_delta(d, 1, t)
    for s in (2, 3):
        t = _churn(t, s)
        save_delta(d, s, t)
    shutil.rmtree(os.path.join(d, "2"))
    assert valid_steps(d) == [1]
    assert "unreadable manifest" in checkpoint_invalid_reason(
        os.path.join(d, "3")
    )


def test_unpromoted_stage_is_invisible(tmp_path):
    """The kill-mid-promote window: blobs + staged manifest durable, no
    finalize rename — the walk must not see the step; a later promote
    (the relaunch's same-step re-save path) finalizes it."""
    d = str(tmp_path / "ck")
    t1 = _tree()
    save_delta(d, 1, t1)
    t2 = _churn(t1, 2)
    staged = stage_delta(d, 2, t2)
    assert staged is not None
    assert valid_steps(d) == [1]  # .tmp-cas-2 invisible by construction
    _assert_tree_equal(restore_state(d, t1), t1)
    promote_delta(d, 2)
    assert valid_steps(d) == [1, 2]
    _assert_tree_equal(restore_state(d, t1), t2)


def test_nonfinite_delta_save_refused(tmp_path):
    d = str(tmp_path / "ck")
    t = _tree()
    t["params"]["head"]["kernel"] = np.full((8, 4), np.nan, np.float32)
    assert save_delta(d, 1, t) is None
    assert valid_steps(d) == []
    assert stage_delta(d, 1, t, write=False) is None  # non-primary verdict


def test_missing_parent_blob_fault_kind(tmp_path):
    """The armed ``missing_parent_blob`` fault deletes a delta-ancestor
    blob after the save finalizes — the chaos contract: the walk falls
    back past the incomplete chain to the last full save."""
    d = str(tmp_path / "ck")
    inject.arm(FaultPlan(missing_parent_blob=3))
    t1 = _tree()
    save_delta(d, 1, t1)
    t2 = _churn(t1, 2, keys=("backbone",))
    save_delta(d, 2, t2)
    t3 = _churn(t2, 3, keys=("head",))
    save_delta(d, 3, t3)  # fault fires here, after finalize
    assert inject.current().missing_parent_blob is None  # one-shot
    assert valid_steps(d) == [1]
    restored, _ = restore_newest(d, t1)
    _assert_tree_equal(restored, t1)


def test_missing_parent_blob_fault_refuses_silent_noop(tmp_path):
    """Armed at a save with no delta-ancestor blobs (the chain base),
    the fault raises instead of proving nothing."""
    d = str(tmp_path / "ck")
    inject.arm(FaultPlan(missing_parent_blob=1))
    with pytest.raises(ValueError, match="no delta-ancestor blobs"):
        save_delta(d, 1, _tree())


def test_fault_plan_parses_new_kinds():
    plan = FaultPlan.from_spec(
        {"kill_mid_delta_promote": 4, "missing_parent_blob": 7}
    )
    assert plan.kill_mid_delta_promote == 4
    assert plan.missing_parent_blob == 7
    assert FaultPlan.from_spec(
        {"kill_mid_delta_promote": True}
    ).kill_mid_delta_promote is True
    with pytest.raises(ValueError, match="kill_mid_delta_promote"):
        FaultPlan.from_spec({"kill_mid_delta_promote": 0})
    with pytest.raises(ValueError, match="missing_parent_blob"):
        FaultPlan.from_spec({"missing_parent_blob": "soon"})


# -------------------------------------------------------- GC + pruning


def test_gc_refcount_matrix(tmp_path):
    """Pruning never breaks a kept/anchor/best chain; orphaned blobs are
    swept; every surviving checkpoint restores bitwise afterwards."""
    root = str(tmp_path / "ck")
    store = blob_store_root(root)
    best_dir = os.path.join(root, "best_gr_4")
    t = _tree()
    trees = {}
    for s in range(1, 6):
        t = _churn(t, s, keys=("head", "backbone") if s == 3 else ("head",))
        trees[s] = t
        save_delta(root, s, t, store_root=store, delta_max_chain=10)
    # anchor + best manifests in their own dirs, SAME blob store
    save_delta(anchor_dir(root), 2, trees[2], store_root=store)
    save_delta(best_dir, 3, trees[3], store_root=store, keep=1)

    # Prune the main dir to the newest 2 (steps 4, 5 — deltas chaining
    # back to the full at 1): chain-aware pruning must keep 1..3 alive
    # as ancestors even though keep=2.
    prune_checkpoints(root, 2)
    assert valid_steps(root) == [1, 2, 3, 4, 5]

    # Orphan a blob: a step dir deleted OUTSIDE the chain-aware prune
    # (simulates an old run's leftovers) leaves its unique blobs
    # unreferenced; GC sweeps them but never a referenced one.
    orphan = _tree(seed=99)
    save_delta(root, 100, orphan, store_root=store)
    resolved = resolve_leaves(os.path.join(root, "100"))
    orphan_blob = _blob_path(
        store,
        resolved.entries["['params']['backbone']['kernel']"][0]["digest"],
    )
    shutil.rmtree(os.path.join(root, "100"))
    assert os.path.exists(orphan_blob)
    swept, _ = gc_blobs(store, min_age_s=0)
    assert swept >= 1 and not os.path.exists(orphan_blob)

    # Everything still referenced survives: main chain, anchor, best.
    for s in (1, 2, 3, 4, 5):
        _assert_tree_equal(restore_state(root, trees[1], step=s), trees[s])
    _assert_tree_equal(
        restore_state(anchor_dir(root), trees[2], step=2), trees[2]
    )
    _assert_tree_equal(restore_state(best_dir, trees[3], step=3), trees[3])
    assert cas_invalid_reason(os.path.join(root, "5")) is None


def test_chain_aware_prune_after_full_rolls_forward(tmp_path):
    """Once the chain cap inserts a new full save, pruning CAN drop the
    old chain — and GC then sweeps its unique blobs."""
    root = str(tmp_path / "ck")
    store = blob_store_root(root)
    t = _tree()
    for s in range(1, 6):
        t = _churn(t, s)
        save_delta(root, s, t, store_root=store, delta_max_chain=2)
    # modes: full(1) d(2) d(3) full(4) d(5); keep=2 keeps 4,5 whose
    # chain needs only 4 — 1..3 prune away.
    prune_checkpoints(root, 2)
    assert valid_steps(root) == [4, 5]
    swept, swept_bytes = gc_blobs(store, min_age_s=0)
    assert swept >= 1 and swept_bytes > 0
    _assert_tree_equal(restore_state(root, t), t)


def test_prune_protects_staged_delta_chain(tmp_path):
    """An in-flight ``.tmp-cas-*`` stage chains to finalized parents
    (multi-host: staged, awaiting the save-done consensus).  A prune
    triggered by a LATER full save must not delete the stage's chain
    out from under it — the promote would find a torn parent."""
    root = str(tmp_path / "ck")
    t1 = _tree()
    save_delta(root, 1, t1)
    t2 = _churn(t1, 2)
    save_delta(root, 2, t2)
    t3 = _churn(t2, 3)
    assert stage_delta(root, 3, t3) is not None  # staged, unpromoted
    t4 = _churn(t3, 4)
    save_delta(root, 4, t4, delta_max_chain=0)  # full; no ancestors
    # keep=1 keeps only the full at 4 — but the staged 3 still needs
    # 2 -> 1, so the chain-aware prune must leave them alone.
    prune_checkpoints(root, 1)
    assert valid_steps(root) == [1, 2, 4]
    promote_delta(root, 3)  # the delayed consensus finally lands
    _assert_tree_equal(restore_state(root, t1, step=3), t3)


def test_gc_age_guard_protects_young_blobs(tmp_path):
    root = str(tmp_path / "ck")
    store = blob_store_root(root)
    t1 = _tree()
    save_delta(root, 1, t1, store_root=store)
    save_delta(root, 2, _tree(seed=5), store_root=store)
    shutil.rmtree(os.path.join(root, "2"))  # its unique blobs orphan
    swept, _ = gc_blobs(store)  # default min age: freshly written = safe
    assert swept == 0
    swept, _ = gc_blobs(store, min_age_s=0)
    assert swept >= 1
    _assert_tree_equal(restore_state(root, t1, step=1), t1)


def test_gc_refuses_sweep_with_zero_manifests(tmp_path):
    """Fail safe: a store with NO referencing manifests under its root
    is either abandoned or mis-sited (a wrong store_root) — sweeping it
    would invalidate every checkpoint that really references it, so GC
    refuses instead of guessing."""
    root = str(tmp_path / "ck")
    store = blob_store_root(root)
    save_delta(root, 1, _tree(), store_root=store)
    shutil.rmtree(os.path.join(root, "1"))  # last manifest gone
    swept, _ = gc_blobs(store, min_age_s=0)
    assert swept == 0  # refused: nothing referenced anything
    blobs = [
        f for d in os.listdir(store)
        for f in os.listdir(os.path.join(store, d))
    ]
    assert blobs  # untouched


def test_gc_multi_root_refcount_unions_sibling_runs(tmp_path):
    """Cross-run GC (the sweep's shared store): blobs referenced only by
    a SIBLING run survive as long as that run's manifest root is in the
    union — and become sweepable the moment it is dropped.  This is
    exactly why per-job local GC is disabled on shared stores: one
    run's view cannot see its siblings' references."""
    store = str(tmp_path / "blobs")
    run_a = str(tmp_path / "a" / "ck")
    run_b = str(tmp_path / "b" / "ck")
    ta, tb = _tree(seed=1), _tree(seed=2)
    save_delta(run_a, 1, ta, store_root=store)
    save_delta(run_b, 1, tb, store_root=store)
    b_blob = _blob_path(
        store,
        resolve_leaves(os.path.join(run_b, "1"))
        .entries["['params']['backbone']['kernel']"][0]["digest"],
    )

    # Union view: every blob is referenced by SOME run — nothing swept.
    swept, _ = gc_blobs(store, min_age_s=0, manifest_roots=[run_a, run_b])
    assert swept == 0 and os.path.exists(b_blob)

    # run_a's view alone (what a job-side GC would see): run_b's unique
    # blobs look orphaned and are swept — run_b is now torn.
    swept, _ = gc_blobs(store, min_age_s=0, manifest_roots=[run_a])
    assert swept >= 1 and not os.path.exists(b_blob)
    _assert_tree_equal(restore_state(run_a, ta, step=1), ta)
    assert cas_invalid_reason(os.path.join(run_b, "1")) is not None


def test_chain_cap_zero_disables_chaining(tmp_path):
    d = str(tmp_path / "ck")
    t = _tree()
    for s in (1, 2, 3):
        t = _churn(t, s)
        save_delta(d, s, t, delta_max_chain=0)
        assert _manifest(d, s)["mode"] == "full"


# -------------------------------------- cross-plan / topology elasticity


def test_topology_change_restore_matrix(tmp_path):
    """A delta checkpoint saved under one topology restores under
    others: (a) single-plan save -> model-sharded restore-to-spec (the
    leaves LAND on their target shardings, streamed per shard); (b) a
    gathered model-sharded save -> a DIFFERENT mesh shape; (c) back to
    an unsharded plan.  Parity = per-leaf digest match after gather."""
    from jax.sharding import PartitionSpec as P  # noqa: F401

    from dwt_tpu.parallel import PRESETS, ShardingPlan, make_plan_mesh

    _, state = _lenet_state()
    d = str(tmp_path / "ck")
    save_delta(d, 3, host_fetch(state))
    want_digest = params_digest(jax.device_get(state.params))

    # (a) restore-to-spec under a (1, 4, 2) model plan
    plan_a = ShardingPlan.gspmd(
        make_plan_mesh((1, 4, 2)), PRESETS["model"], name="model"
    )
    sh_a = plan_a.restore_shardings(state)
    ra = restore_state(d, state, shardings=sh_a)
    kernel = ra.params["conv1"]["kernel"]
    assert kernel.sharding == sh_a.params["conv1"]["kernel"]
    assert kernel.addressable_shards[0].data.shape[-1] == 16  # 32 / model 2
    assert params_digest(
        jax.device_get(plan_a.gather(ra).params)
    ) == want_digest

    # (b) save the sharded state (gathered), restore under (1, 2, 4)
    d2 = str(tmp_path / "ck2")
    placed = plan_a.place(ra, "state")
    save_delta(d2, 3, host_fetch(plan_a.gather(placed)))
    plan_b = ShardingPlan.gspmd(
        make_plan_mesh((1, 2, 4)), PRESETS["model"], name="model"
    )
    rb = restore_state(d2, state, shardings=plan_b.restore_shardings(state))
    assert rb.params["conv1"]["kernel"].addressable_shards[0].data.shape[-1] \
        == 8  # 32 / model 4
    assert params_digest(
        jax.device_get(plan_b.gather(rb).params)
    ) == want_digest

    # (c) cross-plan back down: no shardings -> uncommitted leaves
    rc = restore_state(d2, state)
    _assert_tree_equal(rc, state)


# ------------------------------------------------------------- consumers


def test_watcher_emits_delta_candidates_unchanged_key(tmp_path):
    from dwt_tpu.fleet.watcher import CheckpointWatcher, newest_candidate

    _, state = _lenet_state()
    d = str(tmp_path / "ck")
    host = host_fetch(state)
    save_delta(d, 1, host)
    cand = newest_candidate(d)
    assert cand.step == 1
    assert cand.digest == _manifest(d, 1)["params_digest"]

    watcher = CheckpointWatcher(d)
    watcher.prime(cand)
    host2 = host_fetch(state.replace(step=state.step + 1))
    save_delta(d, 2, host2)
    nxt = watcher.poll_once()
    assert nxt is not None and nxt.step == 2
    assert watcher.poll_once() is None  # dedup key unchanged: no re-emit
    # Same-step re-save with moved params IS a new candidate.
    bumped = state.replace(
        step=state.step + 1,
        params=jax.tree.map(lambda x: x * 1.5, state.params),
    )
    save_delta(d, 2, host_fetch(bumped))
    again = watcher.poll_once()
    assert again is not None and again.step == 2
    assert again.digest != nxt.digest


def test_serve_engine_loads_delta_checkpoint(tmp_path):
    """The serving path's template-free loose restore reads the delta
    format through the same ranked walk, digest-verified."""
    from dwt_tpu.serve.engine import ServeEngine

    model, state = _lenet_state()
    d = str(tmp_path / "ck")
    save_delta(d, 7, host_fetch(state))
    engine = ServeEngine.from_checkpoint(
        d, model, (28, 28, 1), buckets=(4,)
    )
    assert engine.step == int(state.step)
    assert engine.version.digest == params_digest(
        jax.device_get(state.params)
    )
    x = np.random.default_rng(0).normal(size=(3, 28, 28, 1)).astype(
        np.float32
    )
    logits = engine.infer(x)
    assert logits.shape == (3, 10) and np.isfinite(logits).all()


def test_bytes_counter_and_heartbeat_fields(tmp_path):
    from dwt_tpu.obs.registry import get_registry
    from dwt_tpu.utils.metrics import HeartbeatEmitter, MetricLogger

    reg = get_registry()
    before = reg.value(
        "dwt_ckpt_bytes_written_total", {"mode": "delta"}
    ) or 0.0
    d = str(tmp_path / "ck")
    t = _tree()
    save_delta(d, 1, t)
    save_delta(d, 2, _churn(t, 2))
    full = reg.value("dwt_ckpt_bytes_written_total", {"mode": "full"})
    delta = reg.value("dwt_ckpt_bytes_written_total", {"mode": "delta"})
    assert full and full > 0
    assert delta is not None and delta > before

    jsonl = str(tmp_path / "hb.jsonl")
    logger = MetricLogger(jsonl_path=jsonl)
    hb = HeartbeatEmitter(logger, every=1)
    hb.step(1)
    hb.step(2)  # second step emits
    logger.close()
    with open(jsonl) as f:
        records = [json.loads(line) for line in f if line.strip()]
    beats = [r for r in records if r["kind"] == "heartbeat"]
    assert beats and beats[-1]["ckpt_bytes_written"] >= delta


def test_cli_flags_reach_config():
    from dwt_tpu.cli.officehome import build_parser as oh_parser
    from dwt_tpu.cli.usps_mnist import build_parser, config_from_args

    args = build_parser().parse_args(
        ["--synthetic", "--ckpt_format", "delta", "--delta_max_chain", "3"]
    )
    cfg = config_from_args(args)
    assert cfg.ckpt_format == "delta" and cfg.delta_max_chain == 3
    assert config_from_args(
        build_parser().parse_args(["--synthetic"])
    ).ckpt_format == "full"  # byte-compat default
    oh = oh_parser().parse_args(["--synthetic", "--ckpt_format", "delta"])
    assert oh.ckpt_format == "delta"


def test_tree_bytes_and_dir_gauge(tmp_path):
    d = str(tmp_path / "ck")
    save_delta(d, 1, _tree())
    measured = tree_bytes(d)
    assert measured > 0
    # du agrees with the manifest's own accounting to within the JSON
    # manifest overhead.
    assert measured >= _manifest(d, 1)["bytes_written"]


# --------------------------------------------- topology-elastic E2E (slow)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _read_jsonl(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


@pytest.mark.slow
def test_two_process_topology_elastic_resume(tmp_path):
    """The relaunch-on-whatever-capacity-exists case: a delta checkpoint
    written by a 1-process run resumes on a 2-process topology (and the
    2-process run keeps delta-saving through the collective-free
    multi-host delta writer)."""
    ck = str(tmp_path / "shared_ck")
    base_args = [
        "--synthetic", "--synthetic_size", "64", "--group_size", "4",
        "--source_batch_size", "8", "--target_batch_size", "8",
        "--test_batch_size", "8", "--num_workers", "0",
        "--ckpt_dir", ck, "--ckpt_every_epochs", "1",
        "--ckpt_format", "delta",
    ]
    env1 = {k: v for k, v in os.environ.items()
            if k != "PALLAS_AXON_POOL_IPS"}
    env1.update(JAX_PLATFORMS="cpu",
                PYTHONPATH=REPO + os.pathsep + env1.get("PYTHONPATH", ""))
    out = subprocess.run(
        [sys.executable, "-m", "dwt_tpu.cli.usps_mnist",
         *base_args, "--epochs", "1"],
        env=env1, capture_output=True, text=True, timeout=300, cwd=REPO,
    )
    assert out.returncode == 0, out.stdout[-3000:] + out.stderr[-2000:]
    assert _manifest(ck, 8)["format"] == "cas_delta"

    port = _free_port()
    procs, logs = [], []
    for rank in range(2):
        env = dict(env1)
        env.update(
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            DWT_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            DWT_NUM_PROCESSES="2",
            DWT_PROCESS_ID=str(rank),
        )
        jsonl = str(tmp_path / f"metrics_{rank}.jsonl")
        logs.append(jsonl)
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "dwt_tpu.cli.usps_mnist",
             *base_args, "--epochs", "2",
             "--distributed", "--data_parallel",
             "--metrics_jsonl", jsonl],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, cwd=REPO,
        ))
    outs = []
    try:
        for p in procs:
            o, _ = p.communicate(timeout=480)
            outs.append(o)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("2-process resume timed out (collective deadlock?)")
    for p, o in zip(procs, outs):
        assert p.returncode == 0, f"rank failed:\n{o[-3000:]}"

    rec0, rec1 = (_read_jsonl(p) for p in logs)

    def _last(records, kind):
        matches = [r for r in records if r["kind"] == kind]
        assert matches, f"no {kind!r} record"
        return matches[-1]

    # Both ranks resumed the 1-process delta checkpoint at step 8…
    assert _last(rec0, "resume")["step"] == _last(rec1, "resume")["step"] == 8
    # …and trained in lockstep to identical params.
    assert (
        _last(rec0, "params_digest")["digest"]
        == _last(rec1, "params_digest")["digest"]
        != 0.0
    )
    # The 2-process run's own saves went through the multi-host delta
    # writer: newest step is a finalized cas manifest (process 0 wrote
    # blobs + manifest; promotion rode the consensus).
    assert _manifest(ck, 16)["format"] == "cas_delta"
