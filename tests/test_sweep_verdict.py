"""Tier-5 verdict-path CI dry-run (VERDICT.md next-round #6).

Drives ``dwt-officehome-sweep --expect_table`` END-TO-END — argument
parsing, per-pair dispatch, results JSON, ``sweep_verdicts``, the verdict
table printing, and the exit-code contract — with the per-pair training
stubbed to canned accuracies, so the whole tier-5 decision path runs in
milliseconds without a dataset or a model.
"""

import json

import pytest

from dwt_tpu.cli import officehome as _oh
from dwt_tpu.cli import officehome_sweep as sweep


@pytest.fixture
def stub_runs(monkeypatch):
    """Replace per-pair training with canned accuracies keyed by the
    metrics filename tag the sweep assigns each pair."""
    calls = []

    def install(accuracies):
        def fake_run(args):
            # The sweep mutates args per pair; the jsonl tag carries the
            # pair identity on the --synthetic path (no dataset paths).
            tag = args.metrics_jsonl or f"pair{len(calls)}"
            calls.append(tag)
            for key, acc in accuracies.items():
                if key in tag:
                    return acc
            raise AssertionError(f"unexpected pair invocation: {tag}")

        monkeypatch.setattr(_oh, "run_from_args", fake_run)
        return calls

    return install


def _base_argv(tmp_path, results):
    return [
        "--synthetic",
        "--pairs", "Art:Clipart,Clipart:Art",
        "--metrics_jsonl", str(tmp_path / "m.jsonl"),
        "--results_json", str(results),
    ]


def test_sweep_verdict_all_ok_and_results_json(
    tmp_path, stub_runs, capsys
):
    table = tmp_path / "expect.json"
    # One checked pair (within ±0.3 of the canned 50.1), one null (the
    # paper value not yet transcribed -> counted as skipped, not failed).
    table.write_text(
        json.dumps({"Art->Clipart": 50.0, "Clipart->Art": None})
    )
    stub_runs({"Art2Clipart": 50.1, "Clipart2Art": 47.7})
    results = tmp_path / "sweep.json"

    mean = sweep.main(
        _base_argv(tmp_path, results) + ["--expect_table", str(table)]
    )
    assert mean == pytest.approx((50.1 + 47.7) / 2)

    out = capsys.readouterr().out
    assert "[verdict] Art->Clipart:" in out and "OK" in out
    assert "no expectation" in out  # the null entry's skip line
    assert "checked=1 skipped=1 all_ok=True" in out

    payload = json.loads(results.read_text())
    assert payload["pairs"]["Art->Clipart"] == pytest.approx(50.1)
    assert payload["verdicts"]["all_ok"] is True
    assert payload["verdicts"]["pairs"]["Art->Clipart"]["ok"] is True


def test_sweep_verdict_failure_exits_nonzero(tmp_path, stub_runs, capsys):
    table = tmp_path / "expect.json"
    table.write_text(
        json.dumps({"Art->Clipart": 60.0, "Clipart->Art": 47.5})
    )
    stub_runs({"Art2Clipart": 50.1, "Clipart2Art": 47.7})
    results = tmp_path / "sweep.json"

    with pytest.raises(SystemExit) as e:
        sweep.main(
            _base_argv(tmp_path, results) + ["--expect_table", str(table)]
        )
    assert e.value.code == 1

    out = capsys.readouterr().out
    assert "FAIL" in out and "all_ok=False" in out
    # The results JSON still records the verdicts of the failed sweep —
    # the artifact a CI job attaches.
    payload = json.loads(results.read_text())
    assert payload["verdicts"]["all_ok"] is False
    assert payload["verdicts"]["pairs"]["Art->Clipart"]["ok"] is False
    assert payload["verdicts"]["pairs"]["Clipart->Art"]["ok"] is True


def test_sweep_rejects_unknown_expectation_keys(tmp_path, stub_runs):
    """A typo'd table key must fail fast BEFORE any pair trains."""
    table = tmp_path / "expect.json"
    table.write_text(json.dumps({"Art->Porduct": 50.0}))
    calls = stub_runs({})
    with pytest.raises(SystemExit, match="match no planned pair"):
        sweep.main(
            _base_argv(tmp_path, tmp_path / "r.json")
            + ["--expect_table", str(table)]
        )
    assert calls == []  # nothing trained


def test_sweep_rejects_single_run_expect_accuracy(tmp_path, stub_runs):
    calls = stub_runs({})
    with pytest.raises(SystemExit, match="expect_table"):
        sweep.main(
            _base_argv(tmp_path, tmp_path / "r.json")
            + ["--expect_accuracy", "50.0"]
        )
    assert calls == []
