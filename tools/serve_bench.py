"""Open-loop serving load generator: latency vs offered load (ISSUE-7).

Closed-loop clients (send, wait, send) hide queueing collapse — the
client slows down exactly when the server does, so the measured latency
stays flat while real users would be timing out.  This bench is
OPEN-loop: request arrival times are a Poisson process at the offered
rate, drawn up front and honored regardless of how the server is doing
(the "millions of users" model — arrivals don't care about your queue).

For each offered load it reports ONE JSON line::

    {"kind": "serve_bench", "offered_imgs_per_s": 400,
     "achieved_imgs_per_s": 398.2, "served": 1991, "shed": 0,
     "shed_rate": 0.0, "e2e_ms_p50": 3.1, "e2e_ms_p95": 4.9,
     "e2e_ms_p99": 6.2, "queue_ms_p50": ..., "device_ms_p50": ...}

sweeping ``--loads`` (imgs/s).  Run one load well past saturation to see
the load-shedding contract: shed_rate rises, the SERVED tail latency
stays bounded (the queue cannot grow past ``--max_queue``), and the
process stays healthy — instead of the unbounded-queue death spiral.

In-process by default (``ServeClient`` — no HTTP overhead, measures the
batcher+engine path the server wraps).  CPU numbers are a functional
floor; the chip round re-runs this against the TPU roofline (PERF.md
"Serving path").

Reduced-precision curves ride the inherited server flags: a sweep run
with ``--serve_dtype bf16`` and/or ``--quantize_int8`` measures the
bf16-bucket / int8-weight engine (the same ``build_engine`` path
``dwt-serve`` uses) and RE-publishes the headline numbers under
precision-tagged keys (``bf16_imgs_per_sec``, ``int8_imgs_per_sec``,
``*_e2e_ms_p99``) plus a ``precision`` field — so an f32 baseline JSONL
and a reduced-precision run coexist in one ``tools/obs_diff.py`` gate
without the per-load keys colliding.

``--reload_every N`` (with ``--ckpt_dir``) hot-swaps the newest
checkpoint every N seconds DURING each load — the continuous-deployment
fleet's restore → build → canary → atomic-swap path under traffic —
and splits the served tail into swap-window vs steady-state percentiles
(PERF.md "Fleet").

``--adapt_every N`` (inherited server flag) runs the online
domain-adaptation loop DURING each load: the dispatcher feeds live
batches to the stat accumulator, and every N seconds an adapted
generation goes through the same canary → swap pipeline.  The record
splits the tail the same way (``adapt_swap_e2e_ms_p99`` vs
``adapt_steady_e2e_ms_p99``, same ``--swap_window_s``) and adds
``adapt_generations`` (canary-accepted folds this load) — the
adaptation-cadence-cost probe (PERF.md "Online adaptation").  A
``DWT_FAULT_PLAN`` with ``serve_drift_shift`` / ``serve_poison_requests``
perturbs the generated traffic per request index, so one bench run can
drive the adapt-under-shift (or under-poison) scenario end to end.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

# Allow `python tools/serve_bench.py` from any cwd in a source checkout.
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _build_client(args):
    # One engine-construction path for the server AND the bench: the
    # bench must measure exactly the engine `dwt-serve` would run.
    from dwt_tpu.serve.server import ServeClient, build_engine

    engine = build_engine(args)
    client = ServeClient(
        engine,
        max_batch_delay_ms=args.max_batch_delay_ms,
        max_queue_items=args.max_queue,
    )
    return client, engine.input_shape


def _apply_spike(gaps) -> None:
    """Fold an armed ``traffic_spike`` fault into the Poisson gaps.

    A spike is a STEP in the offered rate, not a burst of extra
    requests: from ``at_request`` onward every inter-arrival gap is
    divided by ``factor`` (rate × factor) before the cumsum, so the
    arrival process stays Poisson — just faster — and the request count
    is unchanged (the open-loop contract still decides what sheds).
    No-op when no plan is armed.
    """
    from dwt_tpu.resilience import inject

    spike = inject.traffic_spike()
    if not spike:
        return
    at = min(int(spike["at_request"]), len(gaps))
    gaps[at:] /= float(spike["factor"])


def run_load(client, input_shape, offered: float, seconds: float,
             request_n: int, seed: int = 0,
             reloader=None, reload_every_s: float = 0.0,
             swap_window_s: float = 0.5, adapter=None) -> dict:
    """One open-loop measurement at ``offered`` imgs/s for ``seconds``.

    Arrivals are Poisson (exponential gaps) in REQUEST units
    (``offered / request_n`` requests/s); each request is ``request_n``
    images of noise (serving cost is shape-, not content-, dependent).
    Shed requests are counted, not retried — the open-loop contract.

    ``reloader`` + ``reload_every_s``: a hot-swap thread force-redeploys
    the newest checkpoint every ``reload_every_s`` seconds DURING the
    load (a same-checkpoint swap — numerically a no-op, operationally
    the full restore → build → swap path).  The record then splits the
    latency tail into ``swap_*`` (requests resolved within
    ``swap_window_s`` after a swap, sliced on the access log's
    resolution stamps) vs ``steady_*`` — the swap-cost-under-load probe.

    ``adapter``: a started :class:`~dwt_tpu.serve.adapt.DomainAdapter`
    already attached to ``client``.  Its swaps are detected by polling
    the accepted-generation counter (the adapter runs on its own
    cadence thread; the bench only observes), timestamped on the same
    resolution-stamp timebase, and split into ``adapt_swap_*`` vs
    ``adapt_steady_*`` with the same window.
    """
    from dwt_tpu.resilience import inject
    from dwt_tpu.serve.batcher import ShedError

    rng = np.random.default_rng(seed)
    req_rate = offered / request_n
    n_requests = max(1, int(round(req_rate * seconds)))
    gaps = rng.exponential(1.0 / req_rate, size=n_requests)
    _apply_spike(gaps)
    arrivals = np.cumsum(gaps)
    x = rng.normal(size=(request_n,) + tuple(input_shape)).astype(np.float32)

    shed, errors = 0, 0
    futures = []
    # Per-request latencies come from the ACCESS LOG (stamped at
    # resolution time by the dispatcher, before the future resolves),
    # not from harvest-time arithmetic — a request that resolved seconds
    # before its future is read must not book those idle seconds as
    # latency.  Count-diffed windows isolate THIS load point's samples
    # from earlier sweep points and the warmup.
    before = client.access_log.windows()
    done = threading.Event()
    swap_ts = []  # resolution-stamp timebase (seconds since log t0)

    def _swap_loop():
        while not done.wait(reload_every_s):
            try:
                # Stamp AFTER the deploy returns: the restore/build runs
                # concurrently with serving (its contention shows in the
                # overall tail); the swap window measures the pointer
                # flip's own impact on in-flight traffic.
                if reloader.reload_newest(force=True, skip_canary=False):
                    swap_ts.append(
                        time.perf_counter() - client.access_log.t0
                    )
            except Exception as e:  # keep the bench honest, not dead
                print(f"serve_bench: swap failed: {e}", file=sys.stderr)

    adapt_ts = []  # adapted-swap stamps, same timebase as swap_ts
    gen0 = adapter.generation if adapter is not None else 0

    def _adapt_watch():
        # Observe, don't drive: the adapter folds on its own thread; a
        # 50 ms poll of the accepted-generation counter timestamps each
        # swap well inside the 0.5 s attribution window.
        seen = gen0
        while not done.wait(0.05):
            gen = adapter.generation
            if gen > seen:
                adapt_ts.extend(
                    [time.perf_counter() - client.access_log.t0]
                    * (gen - seen)
                )
                seen = gen

    def _submit_all():
        nonlocal shed
        t0 = time.perf_counter()
        for i, t_arr in enumerate(arrivals):
            delay = t0 + t_arr - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            # Armed DWT_FAULT_PLAN serving kinds perturb the open-loop
            # traffic itself (no-ops when disarmed): drift first — the
            # world moved — then poison rides the drifted stream.
            xi = inject.maybe_shift_request(i, x)
            xi = inject.maybe_poison_request(i, xi)
            try:
                futures.append(client.submit(xi))
            except ShedError:
                shed += 1

    submitter = threading.Thread(target=_submit_all, daemon=True)
    swapper = None
    if reloader is not None and reload_every_s > 0:
        swapper = threading.Thread(target=_swap_loop, daemon=True)
    watcher = None
    if adapter is not None:
        watcher = threading.Thread(target=_adapt_watch, daemon=True)
        watcher.start()
    t_start = time.perf_counter()
    submitter.start()
    if swapper is not None:
        swapper.start()
    submitter.join()
    # Harvest: every accepted request must resolve (bounded queue + the
    # dispatcher draining it guarantee this terminates promptly).
    for fut in futures:
        try:
            fut.result(timeout=60.0)
        except Exception:
            errors += 1
    # Clock stops when the last request resolves — BEFORE joining the
    # swapper, whose tail reload would otherwise inflate duration_s (and
    # deflate achieved rate) in exactly the reloading arm of the A/B.
    elapsed = time.perf_counter() - t_start
    done.set()
    if swapper is not None:
        swapper.join(timeout=60.0)
    if watcher is not None:
        watcher.join(timeout=60.0)
    after = client.access_log.windows()
    delta = after["served_requests"] - before["served_requests"]

    from dwt_tpu.utils.metrics import percentile_summary

    served = len(futures) - errors
    total = served + shed + errors
    record = {
        "kind": "serve_bench",
        "offered_imgs_per_s": round(offered, 1),
        "duration_s": round(elapsed, 3),
        "request_n": request_n,
        "requests": total,
        "served": served,
        "shed": shed,
        "errors": errors,
        "shed_rate": round(shed / max(total, 1), 4),
        "achieved_imgs_per_s": round(
            served * request_n / max(elapsed, 1e-9), 1
        ),
    }
    for name, qs in (("e2e_ms", (50.0, 95.0, 99.0)),
                     ("queue_ms", (50.0, 99.0)),
                     ("device_ms", (50.0, 99.0))):
        window = after[name][-delta:] if delta > 0 else []
        record.update(percentile_summary(window, qs, prefix=f"{name}_p"))
    if swapper is not None:
        e2e = after["e2e_ms"][-delta:] if delta > 0 else []
        tstamps = after["resolved_t"][-delta:] if delta > 0 else []
        in_swap = [
            v for v, t in zip(e2e, tstamps)
            if any(ts <= t <= ts + swap_window_s for ts in swap_ts)
        ]
        steady = [
            v for v, t in zip(e2e, tstamps)
            if not any(ts <= t <= ts + swap_window_s for ts in swap_ts)
        ]
        record.update(
            swaps=len(swap_ts),
            swap_window_s=swap_window_s,
            swap_requests=len(in_swap),
            **percentile_summary(in_swap, (50.0, 99.0),
                                 prefix="swap_e2e_ms_p"),
            **percentile_summary(steady, (50.0, 99.0),
                                 prefix="steady_e2e_ms_p"),
        )
    if adapter is not None:
        e2e = after["e2e_ms"][-delta:] if delta > 0 else []
        tstamps = after["resolved_t"][-delta:] if delta > 0 else []
        in_adapt = [
            v for v, t in zip(e2e, tstamps)
            if any(ts <= t <= ts + swap_window_s for ts in adapt_ts)
        ]
        adapt_steady = [
            v for v, t in zip(e2e, tstamps)
            if not any(ts <= t <= ts + swap_window_s for ts in adapt_ts)
        ]
        record.update(
            adapt_generations=adapter.generation - gen0,
            adapt_swaps=len(adapt_ts),
            adapt_swap_window_s=swap_window_s,
            adapt_swap_requests=len(in_adapt),
            adapt_fold_attempts=adapter.fold_attempts,
            **percentile_summary(in_adapt, (50.0, 99.0),
                                 prefix="adapt_swap_e2e_ms_p"),
            **percentile_summary(adapt_steady, (50.0, 99.0),
                                 prefix="adapt_steady_e2e_ms_p"),
        )
    return record


def _parse_ramp(spec: str):
    """``lo:hi:step_s`` → (lo, hi, step_s), strictly validated."""
    parts = spec.split(":")
    if len(parts) != 3:
        raise ValueError(f"--ramp wants lo:hi:step_s, got {spec!r}")
    lo, hi, step_s = (float(v) for v in parts)
    if not (lo > 0 and hi >= lo and step_s > 0):
        raise ValueError(
            f"--ramp needs 0 < lo <= hi and step_s > 0, got {spec!r}"
        )
    return lo, hi, step_s


def _ramp_schedule(lo: float, hi: float):
    """Geometric (doubling) rate steps lo → hi, hi always included."""
    rates, r = [], lo
    while r < hi:
        rates.append(r)
        r *= 2.0
    rates.append(hi)
    return rates


def run_ramp(args) -> dict:
    """Open-loop HTTP ramp against a live ``dwt-fleet`` front door.

    The sweep arm measures the engine in-process; this arm measures the
    FLEET — the balancer, its weighted routing, and the autoscaler's
    reaction time are the objects under test, so requests go over real
    HTTP and the fleet's own ``/healthz`` is polled for the first
    ``target_replicas`` increase.  The offered rate steps geometrically
    ``lo → hi`` (each level held ``step_s``), arrivals Poisson within
    each level and honored regardless of how the fleet is doing.

    One ``serve_ramp`` record: ``ramp_scale_lag_s`` (ramp start → first
    observed scale-up), ``ramp_shed_total`` (429/503 answers),
    ``ramp_lost_total`` (no HTTP answer at all — the loss-free contract
    says this stays 0 even while replicas retire), overall and
    post-scale-up served tails, and ``ramp_fast_share`` (largest
    per-replica share of served requests, off the balancer's
    ``X-DWT-Replica`` stamp — the weighted-routing probe).
    """
    import http.client
    import queue
    import urllib.parse

    url = args.target_url
    if "//" not in url:
        url = "http://" + url
    parsed = urllib.parse.urlsplit(url)
    host, port = parsed.hostname, parsed.port or 80

    input_shape = tuple(
        int(v) for v in str(args.input_shape).split(",") if v.strip()
    )
    lo, hi, step_s = _parse_ramp(args.ramp)
    rates = _ramp_schedule(lo, hi)
    rng = np.random.default_rng(args.seed)
    x = rng.normal(
        size=(args.request_n,) + input_shape
    ).astype(np.float32)
    body = json.dumps({"inputs": x.tolist()}).encode()

    results = []  # (t_submit_rel, e2e_ms|None, status|None, rid|None)
    results_lock = threading.Lock()
    jobs: "queue.Queue" = queue.Queue()
    done = threading.Event()
    t0 = time.perf_counter()

    def _worker():
        conn = None
        while True:
            job = jobs.get()
            if job is None:
                return
            t_due = job
            t_send = time.perf_counter()
            status, rid, e2e_ms = None, None, None
            try:
                if conn is None:
                    conn = http.client.HTTPConnection(
                        host, port, timeout=30.0
                    )
                conn.request("POST", "/infer", body,
                             {"Content-Type": "application/json"})
                resp = conn.getresponse()
                resp.read()
                status = resp.status
                rid = resp.getheader("X-DWT-Replica")
                e2e_ms = (time.perf_counter() - t_send) * 1e3
            except Exception:
                # A dead kept-alive conn or a mid-request failure: the
                # request got NO answer — that is exactly what
                # ramp_lost_total counts.  Fresh conn for the next one.
                try:
                    if conn is not None:
                        conn.close()
                except Exception:
                    pass
                conn = None
            with results_lock:
                results.append((t_due - t0, e2e_ms, status, rid))

    # Time-to-first-scale-up watcher: the fleet's own target_replicas
    # gauge (via /healthz) is the autoscaler's decision stamp.
    baseline_target = None
    scale_up_t = [None]

    def _watch():
        nonlocal baseline_target
        while not done.wait(0.1):
            try:
                conn = http.client.HTTPConnection(host, port, timeout=2.0)
                conn.request("GET", "/healthz")
                resp = conn.getresponse()
                h = json.loads(resp.read() or b"{}")
                conn.close()
            except Exception:
                continue
            tgt = h.get("target_replicas")
            if tgt is None:
                continue
            if baseline_target is None:
                baseline_target = tgt
            elif tgt > baseline_target and scale_up_t[0] is None:
                scale_up_t[0] = time.perf_counter() - t0

    workers = [
        threading.Thread(target=_worker, daemon=True)
        for _ in range(args.ramp_workers)
    ]
    for w in workers:
        w.start()
    watcher = threading.Thread(target=_watch, daemon=True)
    watcher.start()

    n_sent = 0
    for rate in rates:
        req_rate = rate / args.request_n
        n = max(1, int(round(req_rate * step_s)))
        gaps = np.random.default_rng(args.seed + n_sent).exponential(
            1.0 / req_rate, size=n
        )
        t_level = time.perf_counter()
        for t_arr in np.cumsum(gaps):
            delay = t_level + t_arr - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            jobs.put(time.perf_counter())
            n_sent += 1
    for _ in workers:
        jobs.put(None)
    for w in workers:
        w.join(timeout=120.0)
    done.set()
    watcher.join(timeout=10.0)

    from dwt_tpu.utils.metrics import percentile_summary

    served = [(t, ms, rid) for t, ms, s, rid in results if s == 200]
    shed = sum(1 for _, _, s, _ in results if s in (429, 503))
    lost = sum(1 for _, _, s, _ in results if s is None)
    per_replica = {}
    for _, _, rid in served:
        per_replica[str(rid)] = per_replica.get(str(rid), 0) + 1
    record = {
        "kind": "serve_ramp",
        "ramp": args.ramp,
        "ramp_rates_imgs_per_s": [round(r, 1) for r in rates],
        "requests": len(results),
        "served": len(served),
        "ramp_shed_total": shed,
        "ramp_lost_total": lost,
        "replica_requests": per_replica,
        **percentile_summary([ms for _, ms, _ in served], (50.0, 99.0),
                             prefix="ramp_e2e_ms_p"),
    }
    if per_replica and len(served) > 0:
        record["ramp_fast_share"] = round(
            max(per_replica.values()) / len(served), 4
        )
    if scale_up_t[0] is not None:
        record["ramp_scale_lag_s"] = round(scale_up_t[0], 2)
        # "Post-scale steady state": requests submitted once the new
        # replica had ~1 s to come up — did adding capacity actually
        # pull the tail back down?
        settle = scale_up_t[0] + 1.0
        record.update(percentile_summary(
            [ms for t, ms, _ in served if t >= settle], (99.0,),
            prefix="ramp_post_scale_e2e_ms_p",
        ))
    return record


def main(argv=None) -> int:
    from dwt_tpu.serve.server import build_parser

    p = argparse.ArgumentParser(
        description="open-loop (Poisson) serving load sweep",
        parents=[build_parser()], conflict_handler="resolve", add_help=True,
    )
    p.add_argument("--loads", default="100,200,400,800",
                   help="comma-separated offered loads (imgs/s) to sweep")
    p.add_argument("--duration_s", type=float, default=5.0,
                   help="measurement window per offered load")
    p.add_argument("--request_n", type=int, default=1,
                   help="images per request")
    p.add_argument("--warmup_requests", type=int, default=8,
                   help="requests served before timing starts")
    p.add_argument("--reload_every", type=float, default=0.0,
                   help="hot-swap the newest --ckpt_dir checkpoint every "
                        "N seconds DURING each load (same-checkpoint "
                        "swap: the numeric no-op / swap-cost probe); the "
                        "record adds swap-window vs steady-state p99")
    p.add_argument("--swap_window_s", type=float, default=0.5,
                   help="window after each swap attributed to it in the "
                        "swap-vs-steady latency split")
    p.add_argument("--ramp", default="",
                   help="lo:hi:step_s — open-loop HTTP ramp against a "
                        "live dwt-fleet front door (--target_url): rate "
                        "doubles lo→hi, each level held step_s; emits "
                        "one serve_ramp record with scale-lag / shed / "
                        "lost / per-replica share (the autoscaler + "
                        "weighted-routing probe)")
    p.add_argument("--target_url", default="",
                   help="fleet front-door URL for --ramp "
                        "(e.g. http://127.0.0.1:8100)")
    p.add_argument("--ramp_workers", type=int, default=32,
                   help="HTTP worker threads for --ramp (each keeps a "
                        "persistent connection)")
    p.add_argument("--input_shape", default="28,28,1",
                   help="input image shape for --ramp payloads (ramp "
                        "mode drives a remote fleet, no local engine)")
    args = p.parse_args(argv)
    if args.reload_every > 0 and not args.ckpt_dir:
        p.error("--reload_every needs --ckpt_dir (the watched directory)")
    if args.ramp:
        if not args.target_url:
            p.error("--ramp needs --target_url (the fleet front door)")
        try:
            _parse_ramp(args.ramp)
        except ValueError as e:
            p.error(str(e))
        print(json.dumps(run_ramp(args)), flush=True)
        return 0

    # Inherited --obs_trace (server parser): every bench run can emit a
    # bucket-attributed serving trace for tools/obs_report.py.
    from dwt_tpu import obs

    obs.maybe_enable(args.obs_trace)
    client, input_shape = _build_client(args)
    reloader = None
    if args.reload_every > 0:
        # The swap path under test is the real one: restore → adapt →
        # cache factorization → plan placement → canary → atomic swap.
        from dwt_tpu.fleet import CanaryGate, HotReloader

        canary_x = np.random.default_rng(args.seed).normal(
            size=(min(8, client.engine.buckets[-1]),) + tuple(input_shape)
        ).astype(np.float32)
        reloader = HotReloader(
            client.engine, args.ckpt_dir,
            access_log=client.access_log,
            canary=CanaryGate(client.engine, canary_x),
        )
    adapter = None
    from dwt_tpu.serve.server import adapt_enabled

    if adapt_enabled(args):
        # The real serve-side adaptation loop: dispatcher hook → stat
        # accumulator → canary → swap, on its own cadence thread.  The
        # bench measures what serving pays for it, per load point.
        from dwt_tpu.serve.server import (
            build_adapter, build_deploy_controller,
        )

        controller = build_deploy_controller(
            args, client.engine, client.access_log
        )
        adapter = build_adapter(
            args, client.engine, client.access_log, controller=controller
        )
        client.attach_adapter(adapter)
        adapter.start()
    rng = np.random.default_rng(args.seed)
    warm = rng.normal(
        size=(args.request_n,) + tuple(input_shape)
    ).astype(np.float32)
    for _ in range(args.warmup_requests):
        client.infer(warm)

    # Precision tags for the reduced-precision curves (PERF.md "Serving
    # path"): both can be set at once (int8 weights + bf16 cache/model).
    from dwt_tpu.serve.server import resolve_serve_dtype

    tags = []
    if getattr(args, "quantize_int8", False):
        tags.append("int8")
    if resolve_serve_dtype(args) == "bf16":
        tags.append("bf16")

    rc = 0
    try:
        for offered in (float(v) for v in args.loads.split(",")):
            record = run_load(
                client, input_shape, offered, args.duration_s,
                args.request_n, seed=args.seed,
                reloader=reloader, reload_every_s=args.reload_every,
                swap_window_s=args.swap_window_s, adapter=adapter,
            )
            if tags:
                record["precision"] = "+".join(tags)
                for tag in tags:
                    if "achieved_imgs_per_s" in record:
                        record[f"{tag}_imgs_per_sec"] = (
                            record["achieved_imgs_per_s"]
                        )
                    if "e2e_ms_p99" in record:
                        record[f"{tag}_e2e_ms_p99"] = record["e2e_ms_p99"]
            print(json.dumps(record), flush=True)
    finally:
        if adapter is not None:
            adapter.stop()  # no adapted swap mid-drain
        client.close(drain=True)
        obs.export()  # no-op unless --obs_trace/DWT_OBS_TRACE
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
