"""Per-request serving metrics: JSONL access records + latency summary.

Every served (or shed) request produces ONE access record — the serving
twin of the training loops' metric stream.  Records are machine-parseable
JSON lines so the same tooling that reads training JSONL reads access
logs, and the aggregate view (p50/p95/p99 latency, imgs/s, shed rate)
is computed with the shared nearest-rank percentile helper in
``dwt_tpu.utils.metrics`` — one percentile definition across training,
eval, consensus, and serving reports.

Access-record schema (all times milliseconds)::

    {"kind": "access", "status": "ok" | "shed" | "error",
     "bucket": 8,          # compiled bucket the batch dispatched into
     "batch_n": 8,         # padded batch size (== bucket)
     "real_n": 5,          # un-padded samples in the batch
     "n": 1,               # samples in THIS request
     "queue_ms": 1.9,      # enqueue -> dispatch (admission + coalescing)
     "device_ms": 3.1,     # H2D-staged dispatch -> logits fetched
     "e2e_ms": 5.4,        # enqueue -> response ready
     "version": "800-3f2a91bc",  # checkpoint step + short params digest
     "batch_seq": 17,      # dispatcher batch counter (batch identity)
     "retry_after_ms": 50} # shed responses only

``queue_ms``/``device_ms`` are batch-level quantities stamped onto every
request that rode the batch; ``e2e_ms`` is per-request.  ``version`` and
``batch_seq`` are the continuous-deployment fleet's audit trail: every
record of one ``batch_seq`` must carry the SAME version (no
mixed-version batch — asserted by tests), and per-version latency/error
windows are what the canary's post-swap rollback reads.

Fleet lifecycle events (``AccessLog.event``) ride the same JSONL stream
with their own ``kind`` (``reload``/``canary``/``swap``/``rollback`` for
the checkpoint deploy path; ``adapt_build``/``adapt_canary``/
``adapt_swap``/``adapt_rollback`` for online-adaptation generations) so
one file tells the whole watch → canary → swap → rollback story —
whichever producer drove the deploy.
"""

from __future__ import annotations

import collections
import json
import logging
import threading
import time
from typing import IO, Optional

from dwt_tpu.obs.registry import get_registry
from dwt_tpu.utils.metrics import percentile_summary

log = logging.getLogger(__name__)

# Aggregation window: enough for a long sustained-load run's tail to be
# measured honestly without unbounded memory on a server that stays up
# for days.
_WINDOW = 100_000

# Per-version latency windows are smaller (rollback verdicts read recent
# behavior, not history) and the version map itself is bounded: a server
# that hot-swaps for days must not grow a dict per superseded version.
_VERSION_WINDOW = 10_000
_MAX_VERSIONS = 8


class _VersionStats:
    """Per-served-version aggregates: the post-swap rollback signal."""

    __slots__ = ("served", "errors", "e2e_ms")

    def __init__(self):
        self.served = 0
        self.errors = 0
        self.e2e_ms = collections.deque(maxlen=_VERSION_WINDOW)


class AccessLog:
    """Thread-safe access-record sink: optional JSONL file + aggregates.

    The dispatcher and front-end threads both write here; a lock (not a
    queue) suffices because records are tiny and the file write is the
    only I/O.  ``jsonl_path=None`` keeps aggregation only (the in-process
    client and the bench use the aggregates; the CLI server also writes
    the file).
    """

    def __init__(self, jsonl_path: Optional[str] = None,
                 stream: Optional[IO] = None):
        self._lock = threading.Lock()
        self._file = open(jsonl_path, "a") if jsonl_path else None
        self._stream = stream
        self._t0 = time.perf_counter()
        self.served_requests = 0
        self.served_imgs = 0
        self.shed_requests = 0
        self.error_requests = 0
        self._e2e_ms = collections.deque(maxlen=_WINDOW)
        self._queue_ms = collections.deque(maxlen=_WINDOW)
        self._device_ms = collections.deque(maxlen=_WINDOW)
        # Resolution stamps (seconds since construction, perf_counter
        # clock), parallel to _e2e_ms: the serve bench slices latency
        # windows around swap times with these — swap-window p99 vs
        # steady-state needs to know WHEN each sample resolved.
        self._resolved_t = collections.deque(maxlen=_WINDOW)
        # Per-version windows, insertion-ordered so the oldest version
        # falls off once the map is full.
        self._versions: "collections.OrderedDict[str, _VersionStats]" = \
            collections.OrderedDict()
        self._write_failed = False  # warn once, not per record
        # Disk-full drops were warn-once and then INVISIBLE: count every
        # lost record so summary()/ /stats / /metrics keep reporting the
        # hole long after the one log line scrolled away.
        self.lost_records = 0
        # Live metrics plane: request counters + per-bucket latency
        # histograms on the process-wide registry (get-or-create is
        # idempotent, so many AccessLog instances share the families;
        # children are cached per instance — the record() hot path pays
        # one dict lookup + a locked add per sample).
        reg = get_registry()
        self._m_requests = reg.counter(
            "dwt_serve_requests_total", "serving requests by outcome",
            labelnames=("status",),
        )
        self._m_imgs = reg.counter(
            "dwt_serve_imgs_total", "samples served (ok requests)"
        )
        self._m_lost = reg.counter(
            "dwt_serve_lost_log_records_total",
            "access-log records dropped by failed writes (disk full)",
        )
        self._m_lat = {
            phase: reg.histogram(
                f"dwt_serve_{phase}_ms",
                f"per-request {phase} latency by compiled bucket (ms)",
                labelnames=("bucket",),
            )
            for phase in ("e2e", "queue", "device")
        }
        self._m_req_children = {
            s: self._m_requests.labels(status=s)
            for s in ("ok", "shed", "error")
        }

    def _version_stats_locked(self, version: str) -> _VersionStats:
        vs = self._versions.get(version)
        if vs is None:
            while len(self._versions) >= _MAX_VERSIONS:
                self._versions.popitem(last=False)
            vs = self._versions[version] = _VersionStats()
        return vs

    def record(self, status: str, n: int, **fields) -> None:
        rec = {"kind": "access", "status": status, "n": int(n), **{
            k: (round(float(v), 3) if isinstance(v, float) else v)
            for k, v in fields.items()
        }}
        version = fields.get("version")
        # Registry feed outside the lock: the counters/histograms carry
        # their own per-child locks, and nothing here reads AccessLog
        # state.
        child = self._m_req_children.get(status)
        (child if child is not None
         else self._m_requests.labels(status=status)).inc()
        if status == "ok":
            self._m_imgs.inc(int(n))
            bucket = str(fields.get("bucket", ""))
            for phase in ("e2e", "queue", "device"):
                v = fields.get(f"{phase}_ms")
                if v is not None:
                    self._m_lat[phase].labels(bucket=bucket).observe(
                        float(v)
                    )
        with self._lock:
            if status == "ok":
                self.served_requests += 1
                self.served_imgs += int(n)
                if "e2e_ms" in fields:
                    self._e2e_ms.append(float(fields["e2e_ms"]))
                    self._resolved_t.append(
                        time.perf_counter() - self._t0
                    )
                if "queue_ms" in fields:
                    self._queue_ms.append(float(fields["queue_ms"]))
                if "device_ms" in fields:
                    self._device_ms.append(float(fields["device_ms"]))
                if version is not None:
                    vs = self._version_stats_locked(str(version))
                    vs.served += 1
                    if "e2e_ms" in fields:
                        vs.e2e_ms.append(float(fields["e2e_ms"]))
            elif status == "shed":
                self.shed_requests += 1
            else:
                self.error_requests += 1
                if version is not None:
                    self._version_stats_locked(str(version)).errors += 1
            self._write_locked(rec)

    def event(self, kind: str, **fields) -> None:
        """One fleet lifecycle record (``reload``/``canary``/``swap``/
        ``rollback``…) on the same JSONL stream as the access records —
        the audit trail a post-mortem reads alongside the per-version
        latency windows."""
        rec = {"kind": str(kind), **{
            k: (round(float(v), 3) if isinstance(v, float) else v)
            for k, v in fields.items()
        }}
        with self._lock:
            self._write_locked(rec)

    def _write_locked(self, rec: dict) -> None:
        # Logging is availability-decoupled: record() runs on the
        # dispatcher thread, and a full disk must degrade to lost
        # access records — not to a dead dispatcher that sheds all
        # traffic while inference itself is healthy.
        line = json.dumps(rec) + "\n"
        lost = False
        for sink in (self._file, self._stream):
            if sink is not None:
                try:
                    sink.write(line)
                except (OSError, ValueError) as e:
                    lost = True
                    if not self._write_failed:
                        self._write_failed = True
                        log.warning(
                            "access-log write failed (%s); further "
                            "records may be lost", e,
                        )
        if lost:
            # Warn once, COUNT always: the drop stays visible in
            # summary(), /stats, and the /metrics counter after the one
            # warning scrolled away.
            self.lost_records += 1
            self._m_lost.inc()

    def version_stats(self, version: str) -> dict:
        """Aggregates attributed to ONE served version: the post-swap
        window the canary's rollback verdict reads.  Empty dict when the
        version has served nothing yet."""
        with self._lock:
            vs = self._versions.get(str(version))
            if vs is None:
                return {}
            out = {
                "served": vs.served,
                "errors": vs.errors,
                "error_rate": round(
                    vs.errors / max(vs.served + vs.errors, 1), 4
                ),
            }
            window = list(vs.e2e_ms)
        out.update(percentile_summary(
            window, (50.0, 99.0), prefix="e2e_ms_p"
        ))
        return out

    def summary(self) -> dict:
        """Aggregate view over the run (latencies over the bounded
        window): the /stats response body and the drain-time footer."""
        # Snapshot under the lock, sort/aggregate OUTSIDE it: summary()
        # is a /stats poll, and the dispatcher's record() must not queue
        # behind O(window log window) percentile math on the hot path.
        with self._lock:
            seconds = time.perf_counter() - self._t0
            out = {
                "kind": "serve_summary",
                "served_requests": self.served_requests,
                "served_imgs": self.served_imgs,
                "shed_requests": self.shed_requests,
                "error_requests": self.error_requests,
                "seconds": round(seconds, 3),
                "imgs_per_s": round(
                    self.served_imgs / max(seconds, 1e-9), 1
                ),
                "lost_log_records": self.lost_records,
            }
            windows = [
                ("e2e_ms", list(self._e2e_ms)),
                ("queue_ms", list(self._queue_ms)),
                ("device_ms", list(self._device_ms)),
            ]
            version_windows = {
                v: (vs.served, vs.errors, list(vs.e2e_ms))
                for v, vs in self._versions.items()
            }
        for name, window in windows:
            out.update(percentile_summary(
                window, (50.0, 95.0, 99.0), prefix=f"{name}_p"
            ))
        if version_windows:
            out["versions"] = {
                v: {
                    "served": served,
                    "errors": errors,
                    "error_rate": round(
                        errors / max(served + errors, 1), 4
                    ),
                    **percentile_summary(
                        window, (50.0, 99.0), prefix="e2e_ms_p"
                    ),
                }
                for v, (served, errors, window) in version_windows.items()
            }
        return out

    def windows(self) -> dict:
        """Consistent snapshot of the latency windows plus the lifetime
        served-request count.  The serve bench takes one snapshot before
        and one after each offered-load run and keeps the last
        ``served_after - served_before`` samples of each window — correct
        even after the bounded deques wrap (an index diff would not be),
        so every sweep point reports only its OWN requests' tail.
        ``resolved_t`` (seconds since this log's construction, parallel
        to ``e2e_ms``) lets the bench slice swap windows out of a run."""
        with self._lock:
            return {
                "served_requests": self.served_requests,
                "e2e_ms": list(self._e2e_ms),
                "queue_ms": list(self._queue_ms),
                "device_ms": list(self._device_ms),
                "resolved_t": list(self._resolved_t),
            }

    @property
    def t0(self) -> float:
        """perf_counter origin of ``resolved_t`` stamps (the bench
        converts its swap times onto the same timebase)."""
        return self._t0

    def flush(self) -> None:
        with self._lock:
            if self._file is not None:
                try:
                    self._file.flush()
                except OSError as e:
                    log.warning("access-log flush failed: %s", e)

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                try:
                    self._file.close()
                except OSError as e:
                    log.warning("access-log close failed: %s", e)
                self._file = None
