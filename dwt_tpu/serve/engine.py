"""AOT-bucketed inference engine: the deployment forward, compiled once.

The paper's deployment artifact is the target-branch eval forward —
frozen running stats, domain-specific whitening at test time, no
augmentation (``dwt_tpu.train.steps.make_serve_forward``).  The engine
makes that forward servable:

* **load once**: params + ``batch_stats`` restore from a training
  checkpoint through the SAME newest-valid ranked walk training resume
  uses (``utils.checkpoint.restore_newest`` — main dir + anchors, both
  the Orbax and host-shard on-disk formats, digest-verified), with NO
  optimizer reconstruction (template-free ``restore_tree``);
* **whiten once**: every site's eval whitening matrix precomputes from
  the frozen stats in one batched factorization
  (``evalpipe.make_whiten_cache_fn`` — the eval pipeline's own cache
  builder), then lives on device for the server's lifetime;
* **compile once per bucket**: ``jax.jit(fwd).lower(...).compile()``
  ahead of time for each fixed bucket shape, so the FIRST request of any
  size pays milliseconds, not an XLA compile;
* **device-resident**: params/stats/cache are placed on device at load
  through the run's :class:`~dwt_tpu.parallel.ShardingPlan` — replicated
  replica fan-out under the dp preset, rules-driven model sharding under
  a gspmd plan (whitening stats and the cache stay replicated per the
  preset's contract); per-request traffic is just the bucket batch H2D
  and the logits D2H.  The host-array loose restore plus plan placement
  is serve's restore-to-spec: each leaf lands directly on its target
  sharding, no replicated device intermediate.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np

from dwt_tpu.serve.batcher import DEFAULT_BUCKETS, bucket_for, pad_to_bucket
from dwt_tpu.train.evalpipe import make_whiten_cache_fn
from dwt_tpu.train.steps import make_serve_forward
from dwt_tpu.utils import restore_newest
from dwt_tpu.utils.checkpoint import adapt_tree

log = logging.getLogger(__name__)


class ServeEngine:
    """Compiled bucket forwards over device-resident weights.

    ``input_shape`` is the per-sample shape (e.g. ``(28, 28, 1)`` for
    digits, ``(224, 224, 3)`` for OfficeHome); ``plan`` (the run's
    :class:`~dwt_tpu.parallel.ShardingPlan`) shards every bucket batch's
    sample axis over the plan's data axes — replica fan-out, with bucket
    sizes rounded UP to data-shard multiples so the shards stay equal
    (pad-and-mask keeps the returned logits exact; the model axis never
    shards the batch).  ``mesh=`` is the pre-plan surface, mapped onto
    the equivalent replica-mode dp plan.
    """

    def __init__(
        self,
        model,
        params,
        batch_stats,
        input_shape: Tuple[int, ...],
        *,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        whitener: Optional[str] = None,
        whiten_eps: Optional[float] = None,
        eval_domain: Optional[int] = None,
        plan=None,
        mesh=None,
        input_dtype=np.float32,
        step: Optional[int] = None,
        source: Optional[str] = None,
    ):
        if plan is None:
            from dwt_tpu.parallel import ShardingPlan

            plan = ShardingPlan.from_mesh(mesh)
        self.model = model
        self.input_shape = tuple(input_shape)
        self.input_dtype = np.dtype(input_dtype)
        self.step = step          # checkpoint step served (None: fresh init)
        self.source = source      # "checkpoint" | "anchor" | None
        self._plan = plan
        self._mesh = plan.mesh
        if plan.data_size > 1:
            buckets = sorted({
                -(-int(b) // plan.data_size) * plan.data_size
                for b in buckets
            })
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))

        if whitener is None:
            # The cache must be factorized by the SAME backend the model
            # was built with (swbn caches the tracked matrix itself, the
            # factorizing backends differ in ulps) — read it off the
            # model rather than trusting a separately-passed flag.
            whitener = getattr(model, "whitener", "cholesky")
        if eval_domain is None:
            # The cache's stat branch must be the branch the model's norm
            # sites serve from — read it off the model, don't guess.
            eval_domain = getattr(model, "eval_domain", 1)
        if whiten_eps is None:
            # Same reasoning for the shrinkage eps: a cache factorized
            # with a different eps than the model's in-site path would
            # break the bitwise contract with the uncached eval forward.
            whiten_eps = getattr(model, "whiten_eps", 1e-3)
        cache = make_whiten_cache_fn(whitener, whiten_eps, eval_domain)(
            batch_stats
        )
        forward = make_serve_forward(model)
        self._x_sharding = plan.batch_sharding()
        fwd = plan.make_serve_forward(forward)
        # Device residency: the ONE placement of the run, through the
        # plan.  gspmd places params per the rules table (stats and the
        # cache pin replicated via the preset's contract); single/replica
        # replicate everything — today's paths.  Host arrays land
        # DIRECTLY on their target shardings: serve's restore-to-spec.
        if plan.mode == "gspmd":
            placed = plan.place(
                {"params": params, "batch_stats": batch_stats,
                 "whiten_cache": cache},
                "serve state",
            )
            self.params = placed["params"]
            self.batch_stats = placed["batch_stats"]
            self.cache = placed["whiten_cache"] if cache else cache
        else:
            self.params = plan.place_replicated(params)
            self.batch_stats = plan.place_replicated(batch_stats)
            self.cache = plan.place_replicated(cache) if cache else cache

        self._compiled: Dict[int, object] = {}
        self.compile_s: Dict[int, float] = {}
        jitted = jax.jit(fwd)
        for b in self.buckets:
            spec = jax.ShapeDtypeStruct(
                (b,) + self.input_shape, self.input_dtype,
                sharding=self._x_sharding,
            )
            t0 = time.perf_counter()
            self._compiled[b] = jitted.lower(
                self.params, self.batch_stats, self.cache, spec
            ).compile()
            self.compile_s[b] = round(time.perf_counter() - t0, 3)
        log.info(
            "serve engine ready: buckets %s compiled in %s s (step=%s)",
            self.buckets, self.compile_s, step,
        )

    # -------------------------------------------------------------- loading

    @classmethod
    def from_checkpoint(
        cls,
        ckpt_dir: str,
        model,
        input_shape: Tuple[int, ...],
        **kwargs,
    ) -> "ServeEngine":
        """Restore the newest valid checkpoint (main dir + anchors, either
        on-disk format) and build the engine from its params/stats.

        The restore is template-free (no optimizer reconstruction), so
        the stat structs come back as plain dicts; a one-time
        ``model.init`` provides the typed structure to graft them onto —
        which doubles as structural validation that the checkpoint
        matches the model the server was asked to build."""
        out = restore_newest(ckpt_dir)  # template-free loose restore
        if out is None:
            raise FileNotFoundError(
                f"no restorable checkpoints under {ckpt_dir} (main or "
                "anchors) — nothing to serve"
            )
        tree, source = out
        if not isinstance(tree, dict) or "params" not in tree \
                or "batch_stats" not in tree:
            raise ValueError(
                f"checkpoint under {ckpt_dir} restored without params/"
                "batch_stats — not a TrainState artifact"
            )
        import jax.numpy as jnp

        num_domains = getattr(model, "num_domains", 2)
        sample = jnp.zeros(
            (num_domains, 1) + tuple(input_shape), jnp.float32
        )
        variables = jax.eval_shape(
            lambda: model.init(jax.random.key(0), sample, train=True)
        )
        params = adapt_tree(
            tree["params"], variables["params"], f"{ckpt_dir} params"
        )
        batch_stats = adapt_tree(
            tree["batch_stats"], variables["batch_stats"],
            f"{ckpt_dir} batch_stats",
        )
        step = tree.get("step")
        return cls(
            model, params, batch_stats, input_shape,
            step=None if step is None else int(np.asarray(step)),
            source=source,
            **kwargs,
        )

    # ------------------------------------------------------------ inference

    def stage(self, x: np.ndarray):
        """H2D placement of one bucket batch — the ``transfer`` hook for
        ``prefetch_to_device`` double-buffered staging (server dispatch
        thread overlaps the next batch's H2D with this one's compute)."""
        x = np.ascontiguousarray(x, self.input_dtype)
        if self._x_sharding is None:
            return jax.device_put(x)
        return jax.device_put(x, self._x_sharding)

    def forward(self, x_staged, bucket: int):
        """Compiled forward of one staged bucket batch -> device logits."""
        fn = self._compiled.get(int(bucket))
        if fn is None:
            raise ValueError(
                f"no compiled forward for bucket {bucket} "
                f"(compiled: {self.buckets})"
            )
        return fn(self.params, self.batch_stats, self.cache, x_staged)

    def infer(self, x: np.ndarray, bucket: Optional[int] = None) -> np.ndarray:
        """Convenience synchronous path: pad → stage → forward → fetch.

        ``x`` is ``[n, ...sample]`` with ``n`` ≤ the largest bucket;
        returns the ``[n, classes]`` logits for the REAL rows only.  The
        server's batched path does these stages on separate threads; this
        single-call form serves tests and the in-process client's
        unbatched mode.
        """
        x = np.asarray(x, self.input_dtype)
        n = x.shape[0]
        if bucket is None:
            bucket = bucket_for(n, self.buckets)
        elif n < 1 or n > bucket:
            raise ValueError(f"got {n} samples for bucket {bucket}")
        logits = jax.device_get(
            self.forward(self.stage(pad_to_bucket(x, bucket)), bucket)
        )
        return np.asarray(logits)[:n]
