"""Multi-host execution test: 2 real processes, CPU fake devices.

Executes the --distributed bring-up end-to-end — ``jax.distributed.
initialize`` via DWT_* env vars (``loop.py:_maybe_init_distributed``),
per-process data sharding (``_multihost_data_split`` +
``batch_iterator(shard=...)``), global-batch assembly
(``dp.shard_batch`` → ``make_array_from_process_local_data``), and the
cross-process eval counter allgather (``loop.py:_evaluate``).  These
paths only run when ``jax.process_count() > 1``, so they are untestable
on the in-process 8-device mesh; this spawns two coordinated OS
processes with 4 fake CPU devices each (SURVEY §4.4 extended to §5's
distributed-backend obligation).
"""

import json
import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _read_jsonl(path: str):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def _last(records, kind: str) -> dict:
    matches = [r for r in records if r["kind"] == kind]
    assert matches, f"no {kind!r} record logged"
    return matches[-1]


@pytest.mark.slow
def test_two_process_distributed_digits(tmp_path):
    port = _free_port()
    procs, logs = [], []
    for rank in range(2):
        env = {k: v for k, v in os.environ.items()
               if k != "PALLAS_AXON_POOL_IPS"}
        env.update(
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            DWT_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            DWT_NUM_PROCESSES="2",
            DWT_PROCESS_ID=str(rank),
            PYTHONPATH=REPO + os.pathsep + env.get("PYTHONPATH", ""),
        )
        jsonl = str(tmp_path / f"metrics_{rank}.jsonl")
        logs.append(jsonl)
        procs.append(
            subprocess.Popen(
                [
                    sys.executable, "-m", "dwt_tpu.cli.usps_mnist",
                    "--synthetic", "--synthetic_size", "64",
                    "--distributed", "--data_parallel",
                    # Also exercises the multi-host chunked path:
                    # [k, batch, ...] chunks through shard_batch(
                    # chunked=True) -> make_array_from_process_local_data
                    # with the (None, mesh-axes) spec.
                    "--steps_per_dispatch", "2",
                    "--epochs", "1",
                    "--group_size", "4",
                    "--source_batch_size", "8",
                    "--target_batch_size", "8",
                    "--test_batch_size", "8",
                    "--num_workers", "0",
                    "--metrics_jsonl", jsonl,
                    # SHARED dir (the real-pod layout): orbax must
                    # coordinate one ocdbt artifact across both ranks.
                    "--ckpt_dir", str(tmp_path / "shared_ck"),
                    "--ckpt_every_epochs", "1",
                ],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
                cwd=REPO,
            )
        )
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=480)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("distributed processes timed out (likely a collective "
                    "deadlock — check per-process batch counts)")
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"rank failed:\n{out[-3000:]}"

    rec0, rec1 = (_read_jsonl(path) for path in logs)

    # Eval counters were allgather'd: every process reports the GLOBAL
    # test set (synthetic_size//2 = 32 samples) and the same accuracy.
    t0, t1 = _last(rec0, "test"), _last(rec1, "test")
    assert t0["count"] == 32 and t1["count"] == 32
    assert t0["accuracy"] == t1["accuracy"]
    assert t0["loss"] == pytest.approx(t1["loss"], rel=1e-6)

    # Replicated params stayed in sync across processes.
    d0, d1 = _last(rec0, "params_digest"), _last(rec1, "params_digest")
    assert d0["digest"] == d1["digest"] != 0.0

    # Both processes trained the same number of steps (no ragged tail).
    assert _last(rec0, "test")["step"] == _last(rec1, "test")["step"] > 0

    # The coordinated multi-host checkpoint exists as ONE valid artifact.
    # (Layout varies by runtime: with fully-replicated state some
    # orbax/jax combinations write everything from process 0, others add
    # a per-process ocdbt shard each — validity, not layout, is the
    # contract.)
    from dwt_tpu.utils.checkpoint import is_valid_checkpoint

    step = _last(rec0, "test")["step"]
    ck = tmp_path / "shared_ck" / str(step)
    assert ck.is_dir(), f"no coordinated checkpoint at {ck}"
    assert is_valid_checkpoint(str(ck))
    # Multi-host async saves use the collective-free host-shard format
    # (ISSUE-5): one replica per process, promoted by process 0 once the
    # consensus says every shard is durable.  --no-async_ckpt would
    # produce the coordinated Orbax layout instead.
    assert (ck / "shard_0").exists() and (ck / "shard_1").exists()
    manifest = json.load(open(ck / "manifest.json"))
    assert manifest["format"] == "host_shards"
    assert manifest["process_count"] == 2
