"""Asynchronous checkpoint pipeline: snapshot → digest → write, off the
hot path.

``utils.checkpoint.save_state`` is safe (atomic finalize, manifest digest,
newest-valid fallback) but synchronous: the train loop stalls for a
device→host fetch, a full SHA-256 over the param tree, and an Orbax
serialize + fsync + rename before the next step can dispatch.  At the
flagship's ~8-95 ms/step that multi-second stall at ``ckpt_every`` cadence
is a pure throughput tax that grows with model size.

:class:`AsyncCheckpointer` splits a save into a cheap hot-path half and a
background half:

* **hot path** — :meth:`save` deep-copies the state into fresh
  *non-donated* device buffers (``jnp.copy`` per leaf: dispatch only, no
  host sync — the runtime orders the copy before any later donation of the
  source buffers) and enqueues the task.  The loop dispatches its next
  step immediately.
* **writer thread** — runs the existing ``save_state`` wholesale: finite
  gate, Orbax write, SHA-256 manifest, atomic rename, prune, and (multi-
  host) the process-0-finalize + cross-process barrier.  Reusing the
  primitive keeps the on-disk format byte-identical to a synchronous save,
  so every restore/fallback path is unchanged.

Correctness rules the train loops must follow (and do — ``train/loop.py``):

* **single in-flight** — a second :meth:`save` arriving while one is
  running joins it first (backpressure), never queues unboundedly.
* **rendezvous** — :meth:`flush` joins the in-flight save; required before
  anything that must observe the checkpoint durably on disk: preemption
  save-and-exit, the final save, guard rollback/restore (the newest valid
  checkpoint must include the in-flight one, and the writer must not race
  the restore's directory walk), and best-record updates (``best.json``
  must never point at an artifact that does not exist yet).
* **errors surface, never vanish** — a writer exception is re-raised on
  the next :meth:`save`/:meth:`flush` (the failed save is logged; the new
  save is *not* silently dropped — the caller sees the failure exactly
  like a synchronous save raising).

Multi-host (ISSUE-5): the Orbax-based writer above cannot run there —
it dispatches device work (the finite-gate jit, ``save_state``'s
cross-process barrier) whose launch order relative to the main thread's
train-step collectives is thread-scheduling dependent, and multi-host
JAX requires an identical collective launch order on every process
(mismatch = runtime deadlock).  :class:`MultiHostAsyncCheckpointer` is
the collective-free variant: the MAIN thread takes the jitted snapshot
and fetches it host-side (``checkpoint.host_fetch`` — the whole hot-path
cost); the writer thread is then **pure I/O**, writing only this
process's replica under ``<step tmp>/shard_<proc>/`` (host-shard format,
``utils/checkpoint.py``).  Global finalization is a filesystem
rendezvous driven from step boundaries: each host piggybacks its
"my writer completed save #k" sequence number on the Coordinator's
consensus vector (a sequence, not a step — the same step can be saved
twice), and process 0 promotes a save (validate shards → top-level
manifest → atomic rename) once the agreed min reaches it.  No
collective, no barrier, nothing device-touching ever runs off the main
thread — enforced by ``coord.assert_not_writer_thread`` on every
collective call site.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Optional

import jax
import jax.numpy as jnp

from dwt_tpu import obs

log = logging.getLogger(__name__)


def _count_save_failure(kind: str) -> None:
    """Live-metrics feed for writer/promotion failures: the error also
    surfaces on the next save/flush, but an operator scraping /metrics
    sees the counter move the moment the background half fails."""
    from dwt_tpu.obs.registry import get_registry

    get_registry().counter(
        "dwt_ckpt_save_failures_total",
        "checkpoint writer/promotion failures",
        labelnames=("kind",),
    ).labels(kind=kind).inc()


# One compiled whole-tree copy, not per-leaf eager jnp.copy: eager dispatch
# of ~75 small ops contends with a busy compute queue (measured: the
# per-leaf form stalls 15→170 ms as the dispatch queue deepens; the jitted
# form stays ~1 ms).  jit never donates by default, so the outputs are
# fresh buffers, and it follows the inputs' shardings on DP/multi-host
# states.  Cached per (structure, shapes) by jit itself.
_snapshot_fn = None


def snapshot_state(state: Any) -> Any:
    """Deep-copy ``state`` into fresh non-donated device buffers.

    Dispatch-only: no host transfer, no sync.  The copy must happen on the
    enqueueing thread — JAX orders it before any later donation of the
    source buffers by the next train step, which a copy issued from the
    writer thread could race.
    """
    global _snapshot_fn
    if _snapshot_fn is None:
        _snapshot_fn = jax.jit(lambda s: jax.tree.map(jnp.copy, s))
    return _snapshot_fn(state)


class AsyncCheckpointer:
    """Single-in-flight background checkpoint writer (see module doc).

    Thread model: at most one writer thread alive at a time; ``save``
    joins any previous writer before starting the next (backpressure).
    All public methods are main-thread only — the loops drive saves from
    one thread, so no internal locking is needed beyond the join.
    """

    def __init__(self):
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._error_step: Optional[int] = None
        self._last_path: Optional[str] = None
        self._pending_step: Optional[int] = None

    # ------------------------------------------------------------- internals

    def _prepare(self, snapshot: Any) -> Any:
        """Writer-thread payload from the enqueued device snapshot —
        identity here; the delta subclass host-fetches (single-process:
        a device_get off the main thread is legal, and the writer
        already dispatches device work via ``save_state``)."""
        return snapshot

    def _save_target(self, ckpt_dir: str, step: int, payload: Any,
                     kwargs: dict):
        """One target directory's save; format-specific in subclasses."""
        # Deferred import: utils.checkpoint imports resilience.inject, so a
        # module-level import here would be circular via the package init.
        from dwt_tpu.utils.checkpoint import save_state

        return save_state(ckpt_dir, step, payload, **kwargs)

    def _run(self, targets, step: int, snapshot: Any) -> None:
        try:
            payload = self._prepare(snapshot)
            for ckpt_dir, kwargs in targets:
                # Writer-thread span: the full background save (digest +
                # write + rename) — what the hot path no longer pays,
                # visible per save in the trace timeline.
                with obs.span("ckpt_write", "ckpt", step=int(step)):
                    path = self._save_target(ckpt_dir, step, payload, kwargs)
                if path is not None:  # None = refused (non-finite), no artifact
                    self._last_path = path
        except BaseException as e:  # surfaced on the next enqueue/flush
            self._error = e
            self._error_step = step
            _count_save_failure("write")
            log.warning("async checkpoint save @%d failed: %s", step, e)

    def _join(self) -> None:
        t = self._thread
        if t is not None:
            t.join()
            self._thread = None
            self._pending_step = None

    def _raise_pending(self) -> None:
        if self._error is not None:
            e, step = self._error, self._error_step
            self._error = self._error_step = None
            log.error("surfacing failed async checkpoint save @%s", step)
            raise e

    # ------------------------------------------------------------------ API

    @property
    def in_flight(self) -> Optional[int]:
        """Step of the save currently being written, or None."""
        return self._pending_step

    def save(self, ckpt_dir: str, step: int, state: Any, **kwargs) -> None:
        """Snapshot ``state`` and enqueue its save; returns immediately
        unless a previous save is still in flight (backpressure join).

        ``kwargs`` pass through to ``save_state`` (``keep=``,
        ``require_finite=``).  A previous writer failure is raised HERE,
        before the new save is enqueued, so no failure is ever swallowed
        between rendezvous points.
        """
        self.save_multi([(ckpt_dir, kwargs)], step, state)

    def save_multi(self, targets, step: int, state: Any) -> None:
        """One snapshot, several directory writes in a single writer task.

        ``targets`` is ``[(ckpt_dir, save_state_kwargs), ...]``.  A
        coinciding cadence boundary (periodic save + its same-step anchor)
        must cost the hot path ONE enqueue — two sequential ``save`` calls
        would make the second's backpressure join block the loop for the
        first save's full writer duration, reintroducing the sync stall on
        exactly those steps.
        """
        self._join()
        self._raise_pending()
        snapshot = snapshot_state(state)
        self._pending_step = int(step)
        self._thread = threading.Thread(
            target=self._run,
            args=(list(targets), int(step), snapshot),
            name=f"dwt-ckpt-writer-{int(step)}",
            daemon=True,
        )
        self._thread.start()

    def flush(self) -> Optional[str]:
        """Join the in-flight save (if any); raise its error if it failed.

        Returns the path of the most recent successfully finalized
        checkpoint (None if no save has completed yet).
        """
        self._join()
        self._raise_pending()
        return self._last_path

    def close(self, raise_errors: bool = True) -> None:
        """Final rendezvous.  ``raise_errors=False`` is for abnormal-exit
        cleanup paths where a writer error must not mask the original
        exception (it is still logged by the writer)."""
        if raise_errors:
            self.flush()
            return
        self._join()
        self._error = self._error_step = None


class MultiHostAsyncCheckpointer(AsyncCheckpointer):
    """Collective-free async writer for multi-host runs (module doc).

    Same single-in-flight/backpressure/error contract as the base class;
    what changes is the split of work:

    * :meth:`save_multi` (main thread) — jitted snapshot, host-side fetch
      (``host_fetch`` blocks on the state's producing computation; that
      fetch IS the hot-path cost), enqueue.
    * writer thread — ``save_host_shard`` per target: raw leaf bytes +
      shard manifest under the step's tmp dir.  Pure I/O; the finite
      gate runs on host numpy.  On success the step is recorded as this
      host's ``done_step`` (read by the loops' boundary consensus) and
      its targets queue for promotion.
    * :meth:`promote_up_to` (main thread) — once the consensus says every
      host's shard of step N is durable, process 0 validates + finalizes
      (``promote_host_shards``); other processes just drop the pending
      entry.  Promotion failures surface exactly like writer errors: on
      the next save/flush.
    """

    def __init__(self, gather=None):
        super().__init__()
        self.process_index = jax.process_index()
        self.process_count = jax.process_count()
        # ISSUE-9: a sharding plan's gather fn — allgathers model-sharded
        # leaves back to replicated on the MAIN thread (it is a
        # collective) so host_fetch sees process-replicated arrays and
        # the on-disk shard format is unchanged by model parallelism.
        # Gated by plan.uses_state_sharding (any sharded state axis), so
        # fsdp-preset heads/moments (ISSUE-19) flow through unchanged.
        self._gather = gather
        # Saves are numbered by a per-host sequence counter (identical
        # across hosts: saves come from lockstep control flow).  The
        # done bit gathered by the consensus is a SEQUENCE, not a step:
        # the same step can be saved twice (notice save + cadence save),
        # and a stale same-step done bit must not green-light promotion
        # while a slower host's writer is still rewriting its shard.
        self._seq = 0
        self._done_seq = -1
        # [(seq, step, ckpt_dir, save_state-style kwargs)] completed
        # shard writes awaiting global promotion, oldest first.  Appended
        # by the writer thread, consumed on the main thread — guarded by
        # the single-in-flight join (the writer is dead or quiescent
        # whenever the main thread reads it at a boundary... except
        # between boundaries, so a lock keeps the append/drain race
        # benign).
        self._pending = []
        self._pending_lock = threading.Lock()

    # ------------------------------------------------------------- internals

    def _write_target(self, ckpt_dir: str, step: int, host_tree,
                      kwargs: dict) -> bool:
        """This process's durable contribution to one target — False
        when the finite gate refused (no artifact, no pending entry)."""
        from dwt_tpu.utils.checkpoint import save_host_shard

        return save_host_shard(
            ckpt_dir, step, host_tree, self.process_index,
            require_finite=kwargs.get("require_finite", True),
            data_state=kwargs.get("data_state"),
        )

    def _promote(self, ckpt_dir: str, step: int, kwargs: dict) -> str:
        """Process 0's finalization of one writer-completed target."""
        from dwt_tpu.utils.checkpoint import promote_host_shards

        return promote_host_shards(
            ckpt_dir, step, self.process_count, keep=kwargs.get("keep"),
        )

    def _run(self, targets, seq: int, step: int, host_tree) -> None:
        try:
            for ckpt_dir, kwargs in targets:
                with obs.span("shard_write", "ckpt", step=int(step)):
                    wrote = self._write_target(
                        ckpt_dir, step, host_tree, kwargs
                    )
                if wrote:
                    with self._pending_lock:
                        self._pending.append(
                            (int(seq), int(step), ckpt_dir, dict(kwargs))
                        )
            # Done-bit ordering: the save counts as "done" only after
            # EVERY target's shard is durably written (a promotion
            # triggered between two targets would finalize the first
            # while the second is mid-write).
            self._done_seq = int(seq)
        except BaseException as e:  # surfaced on the next enqueue/flush
            self._error = e
            self._error_step = step
            _count_save_failure("shard_write")
            log.warning("async shard save @%d failed: %s", step, e)

    # ------------------------------------------------------------------ API

    @property
    def done_seq(self) -> int:
        """Sequence number of the newest save THIS host's writer has
        fully completed (-1: none yet).  Fed into the boundary consensus
        vector; the agreed min across hosts is the promotion frontier."""
        return self._done_seq

    def join(self) -> None:
        """Join the in-flight writer WITHOUT raising its error — for
        rendezvous sequencing where collectives must still be issued in
        lockstep before a host-local failure may surface."""
        self._join()

    def save_multi(self, targets, step: int, state) -> None:
        self._join()
        self._raise_pending()
        from dwt_tpu.utils.checkpoint import host_fetch

        # Snapshot + host fetch on the MAIN thread: the fetch blocks on
        # the state's producing computation (the hot-path cost of a
        # multi-host save); an exception here enqueues nothing.  The span
        # is the attribution evidence for exactly that cost.
        with obs.span("ckpt_host_fetch", "ckpt", step=int(step)):
            host_tree = host_fetch(snapshot_state(state), gather=self._gather)
        self._seq += 1
        self._pending_step = int(step)
        self._thread = threading.Thread(
            target=self._run,
            args=(list(targets), self._seq, int(step), host_tree),
            name=f"dwt-ckpt-writer-{int(step)}",
            daemon=True,
        )
        self._thread.start()

    def promote_up_to(self, agreed_seq: int) -> None:
        """Finalize every pending save with sequence <= ``agreed_seq``.

        Main thread only.  ``agreed_seq`` is the consensus min of all
        hosts' ``done_seq`` — by construction every host's writer has
        fully completed those saves, so a failed validation here is a
        real fault (torn shard, dead filesystem) and is queued to
        surface on the next save/flush, after which restore falls back
        past the unpromoted tmp dir.
        """
        if agreed_seq < 0:
            return
        with self._pending_lock:
            due = [p for p in self._pending if p[0] <= agreed_seq]
            self._pending = [p for p in self._pending if p[0] > agreed_seq]
        for _seq, step, ckpt_dir, kwargs in due:
            if self.process_index != 0:
                continue
            try:
                with obs.span("ckpt_promote", "ckpt", step=int(step)):
                    self._last_path = self._promote(ckpt_dir, step, kwargs)
            except OSError as e:
                if self._error is None:
                    self._error = e
                    self._error_step = step
                _count_save_failure("promote")
                log.warning("checkpoint promotion @%d failed: %s", step, e)

    def flush(self):
        """Join the in-flight shard write; raise any writer/promotion
        error.  NOTE: after a multi-host flush the caller still owes the
        finalization rendezvous (gather done-bits → promote → barrier) —
        the loops' ``_CkptPipeline.flush`` owns that sequencing, since
        only the main loop may issue the collectives it needs."""
        return super().flush()


class DeltaAsyncCheckpointer(AsyncCheckpointer):
    """Single-process async writer for the content-addressed delta
    format (``--ckpt_format delta``, ISSUE-13).

    Same single-in-flight/backpressure/error contract; the writer
    host-fetches the device snapshot (legal off the main thread on a
    single process, exactly like the Orbax writer's own device work)
    and hands it to the delta store — which reuses the per-leaf digests
    it computes for content addressing as the manifest diff, so the
    delta decision costs no extra hashing pass."""

    def __init__(self, store_root=None,
                 delta_max_chain: Optional[int] = None, gc: bool = True):
        super().__init__()
        self._store_root = store_root
        self._delta_max_chain = delta_max_chain
        # False on a SHARED store (--blob_store): this run cannot see
        # sibling runs' manifests, so local GC could sweep their blobs.
        self._gc = gc

    def _prepare(self, snapshot: Any) -> Any:
        from dwt_tpu.utils.checkpoint import host_fetch

        return host_fetch(snapshot)

    def _save_target(self, ckpt_dir: str, step: int, payload: Any,
                     kwargs: dict):
        from dwt_tpu.ckpt.store import DEFAULT_DELTA_MAX_CHAIN, save_delta

        return save_delta(
            ckpt_dir, step, payload,
            store_root=self._store_root,
            delta_max_chain=(
                self._delta_max_chain
                if self._delta_max_chain is not None
                else DEFAULT_DELTA_MAX_CHAIN
            ),
            gc=self._gc,
            **kwargs,
        )


class MultiHostDeltaAsyncCheckpointer(MultiHostAsyncCheckpointer):
    """Multi-host async writer for the delta format: identical snapshot
    → main-thread host-fetch (+ plan gather) → writer-thread I/O →
    consensus-driven promotion contract as the host-shard writer.  The
    state arriving at the writer is process-replicated by construction,
    so process 0 writes the blobs + staged manifest for everyone; the
    other ranks run only the finite gate (their accept/refuse verdict
    must match process 0's for the save-done consensus to stay
    consistent, and the state being replicated guarantees it does)."""

    def __init__(self, gather=None, store_root=None,
                 delta_max_chain: Optional[int] = None, gc: bool = True):
        super().__init__(gather=gather)
        self._store_root = store_root
        self._delta_max_chain = delta_max_chain
        self._gc = gc

    def _write_target(self, ckpt_dir: str, step: int, host_tree,
                      kwargs: dict) -> bool:
        from dwt_tpu.ckpt.store import DEFAULT_DELTA_MAX_CHAIN, stage_delta

        staged = stage_delta(
            ckpt_dir, step, host_tree,
            store_root=self._store_root,
            delta_max_chain=(
                self._delta_max_chain
                if self._delta_max_chain is not None
                else DEFAULT_DELTA_MAX_CHAIN
            ),
            require_finite=kwargs.get("require_finite", True),
            write=self.process_index == 0,
            data_state=kwargs.get("data_state"),
        )
        return staged is not None

    def _promote(self, ckpt_dir: str, step: int, kwargs: dict) -> str:
        from dwt_tpu.ckpt.store import promote_delta

        return promote_delta(
            ckpt_dir, step, keep=kwargs.get("keep"),
            store_root=self._store_root, gc=self._gc,
        )
