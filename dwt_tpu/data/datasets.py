"""Datasets: USPS pkl, MNIST, and the class-per-folder image walker.

All datasets expose ``__len__`` and ``__getitem__(i)`` returning
``(img, label)`` or — when a second ``transform_aug`` view is configured —
``(img, img_aug, label)``, the reference's dual-view triple protocol
(``utils/folder.py:138-147``, ``usps_mnist.py:71-82``).
"""

from __future__ import annotations

import gzip
import os
import pickle
import struct
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

# Training-set replication factor for USPS (reference
# ``usps_mnist.py:24``: usps_dataset_multiplier = 6).
USPS_MULTIPLIER = 6

IMG_EXTENSIONS = (
    ".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif", ".tiff", ".webp",
)


def load_usps(
    root: str,
    train: bool = True,
    multiplier: int = USPS_MULTIPLIER,
    seed: int = 0,
) -> Tuple[np.ndarray, np.ndarray]:
    """Load ``usps_28x28.pkl`` → (images ``[N,28,28,1]`` float32 [0,1], labels).

    Mirrors the reference loader (``usps_mnist.py:106-120``): gzip pickle
    with ``[[train_x, train_y], [test_x, test_y]]`` in NCHW; the training
    split is replicated ×6 and shuffled (``:48-55``).  This environment has
    no egress, so the file must already exist (no download path).
    """
    path = root if root.endswith(".pkl") else os.path.join(root, "usps_28x28.pkl")
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"USPS pickle not found at {path}; place usps_28x28.pkl there "
            "(the reference downloads it from the CoGAN repo)"
        )
    with gzip.open(path, "rb") as f:
        dataset = pickle.load(f, encoding="bytes")
    images, labels = dataset[0 if train else 1]
    images = np.asarray(images, np.float32)
    labels = np.asarray(labels, np.int64).reshape(-1)
    if train and multiplier > 1:
        n = labels.shape[0]
        images = np.repeat(images, multiplier, axis=0)
        labels = np.repeat(labels, multiplier, axis=0)
        idx = np.random.default_rng(seed).permutation(multiplier * n)
        images, labels = images[idx], labels[idx]
    # NCHW [N,1,28,28] → NHWC (the reference's transpose at :58; its
    # comment says NCHW but the result is NHWC — SURVEY §7 quirks).
    return images.transpose(0, 2, 3, 1), labels


def load_mnist(root: str, train: bool = True) -> Tuple[np.ndarray, np.ndarray]:
    """Load MNIST → (images ``[N,28,28,1]`` float32 [0,1], labels).

    Accepts either the torchvision-processed ``processed/training.pt`` /
    ``test.pt`` the reference consumes (``usps_mnist.py:139-153``) or the
    raw idx files (``train-images-idx3-ubyte`` etc.) in ``root``.
    """
    name = "training.pt" if train else "test.pt"
    pt_path = os.path.join(root, "processed", name)
    if os.path.exists(pt_path):
        import torch

        data, targets = torch.load(pt_path, weights_only=False)
        images = np.asarray(data.numpy(), np.float32) / 255.0
        labels = np.asarray(targets.numpy(), np.int64)
        return images[..., None], labels

    prefix = "train" if train else "t10k"
    img_path = os.path.join(root, f"{prefix}-images-idx3-ubyte")
    lbl_path = os.path.join(root, f"{prefix}-labels-idx1-ubyte")
    if not os.path.exists(img_path):
        raise FileNotFoundError(
            f"MNIST not found under {root} (neither processed/{name} nor "
            f"{prefix}-images-idx3-ubyte)"
        )
    with open(img_path, "rb") as f:
        _, n, rows, cols = struct.unpack(">IIII", f.read(16))
        images = np.frombuffer(f.read(), np.uint8).reshape(n, rows, cols)
    with open(lbl_path, "rb") as f:
        struct.unpack(">II", f.read(8))
        labels = np.frombuffer(f.read(), np.uint8).astype(np.int64)
    return images.astype(np.float32)[..., None] / 255.0, labels


class ArrayDataset:
    """In-memory dataset over (images, labels) with optional dual view."""

    def __init__(
        self,
        images: np.ndarray,
        labels: np.ndarray,
        transform: Optional[Callable] = None,
        transform_aug: Optional[Callable] = None,
    ):
        assert len(images) == len(labels)
        self.images = images
        self.labels = labels
        self.transform = transform
        self.transform_aug = transform_aug

    def __len__(self) -> int:
        return len(self.labels)

    def __getitem__(self, i: int):
        img = self.images[i]
        label = int(self.labels[i])
        out = self.transform(img) if self.transform else img
        if self.transform_aug is not None:
            return out, self.transform_aug(img), label
        return out, label


def _find_classes(root: str) -> Tuple[List[str], dict]:
    classes = sorted(
        entry.name for entry in os.scandir(root) if entry.is_dir()
    )
    return classes, {c: i for i, c in enumerate(classes)}


def make_dataset(
    root: str, class_to_idx: dict, extensions: Sequence[str] = IMG_EXTENSIONS
) -> List[Tuple[str, int]]:
    """Sorted (path, class_index) walk — reference ``folder.py:40-55``."""
    samples = []
    root = os.path.expanduser(root)
    for cls in sorted(class_to_idx):
        d = os.path.join(root, cls)
        if not os.path.isdir(d):
            continue
        for sub, _, files in sorted(os.walk(d)):
            for name in sorted(files):
                if name.lower().endswith(tuple(extensions)):
                    samples.append((os.path.join(sub, name), class_to_idx[cls]))
    return samples


class ImageFolderDataset:
    """``root/class_x/*.jpg`` walker with the dual-view protocol.

    Matches the reference's vendored folder dataset (``utils/folder.py:58-
    190``): sorted class discovery, recursive sorted sample walk, RGB PIL
    load, and the ``transform_aug`` second view that turns items into
    ``(img, img_aug, label)`` triples (``:138-147``).
    """

    def __init__(
        self,
        root: str,
        transform: Optional[Callable] = None,
        transform_aug: Optional[Callable] = None,
        extensions: Sequence[str] = IMG_EXTENSIONS,
    ):
        classes, class_to_idx = _find_classes(root)
        samples = make_dataset(root, class_to_idx, extensions)
        if not samples:
            raise RuntimeError(
                f"Found 0 images in subfolders of {root} "
                f"(extensions: {','.join(extensions)})"
            )
        self.root = root
        self.classes = classes
        self.class_to_idx = class_to_idx
        self.samples = samples
        self.targets = [t for _, t in samples]
        self.transform = transform
        self.transform_aug = transform_aug

    def __len__(self) -> int:
        return len(self.samples)

    def _load(self, path: str):
        from PIL import Image

        with open(path, "rb") as f:
            return Image.open(f).convert("RGB")

    def __getitem__(self, i: int):
        path, label = self.samples[i]
        img = self._load(path)
        out = self.transform(img) if self.transform else img
        if self.transform_aug is not None:
            return out, self.transform_aug(img), label
        return out, label
