"""Module-layer tests: domain norms and LeNetDWT routing semantics."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dwt_tpu.nn import DomainBatchNorm, DomainWhiten, LeNetDWT
from dwt_tpu.ops import batch_norm, group_whiten


def test_domain_whiten_matches_per_branch_op():
    """Branch d of the module must reproduce group_whiten on slice d."""
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(2, 6, 3, 3, 8)), jnp.float32)
    mod = DomainWhiten(features=8, group_size=4, num_domains=2, use_affine=False)
    variables = mod.init(jax.random.key(0), x, train=True)
    y, updated = mod.apply(variables, x, train=True, mutable=["batch_stats"])

    stats0 = jax.tree.map(
        lambda a: a[0], variables["batch_stats"]["whitening"]
    )
    y0, new0 = group_whiten(x[0], stats0, group_size=4, train=True)
    np.testing.assert_allclose(np.asarray(y[0]), np.asarray(y0), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(updated["batch_stats"]["whitening"].mean[0]),
        np.asarray(new0.mean),
        rtol=1e-5,
        atol=1e-6,
    )


def test_domain_whiten_eval_uses_eval_domain_branch_only():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(5, 3, 3, 8)), jnp.float32)
    mod = DomainWhiten(features=8, group_size=4, num_domains=2, eval_domain=1,
                       use_affine=False)
    variables = mod.init(jax.random.key(0), x[None].repeat(2, 0), train=True)
    # Give the two branches very different stats.
    stats = variables["batch_stats"]["whitening"]
    stats = stats._replace(
        mean=stats.mean.at[0].set(100.0),
        cov=stats.cov.at[0].mul(50.0),
    )
    variables = {"batch_stats": {"whitening": stats}}
    y = mod.apply(variables, x, train=False)
    branch1 = jax.tree.map(lambda a: a[1], stats)
    y1, _ = group_whiten(x, branch1, group_size=4, train=False)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y1), rtol=1e-5, atol=1e-5)
    # And it must NOT equal branch 0's result.
    branch0 = jax.tree.map(lambda a: a[0], stats)
    y0, _ = group_whiten(x, branch0, group_size=4, train=False)
    assert not np.allclose(np.asarray(y), np.asarray(y0))


def test_domain_batch_norm_matches_per_branch_op():
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(3, 8, 16)), jnp.float32)  # [D, N, C]
    mod = DomainBatchNorm(features=16, num_domains=3, use_affine=False)
    variables = mod.init(jax.random.key(0), x, train=True)
    y, updated = mod.apply(variables, x, train=True, mutable=["batch_stats"])
    for d in range(3):
        sd = jax.tree.map(lambda a: a[d], variables["batch_stats"]["bn"])
        yd, nd = batch_norm(x[d], sd, train=True)
        np.testing.assert_allclose(np.asarray(y[d]), np.asarray(yd), rtol=1e-5,
                                   atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(updated["batch_stats"]["bn"].var[d]),
            np.asarray(nd.var), rtol=1e-5, atol=1e-6)


def test_domain_norm_rejects_missing_domain_axis():
    x = jnp.zeros((4, 16))
    mod = DomainBatchNorm(features=16, num_domains=2)
    with pytest.raises(ValueError, match="domain axis"):
        mod.init(jax.random.key(0), x, train=True)


def test_lenet_shapes_and_eval_routing():
    model = LeNetDWT(group_size=4)
    x_train = jnp.asarray(
        np.random.default_rng(3).normal(size=(2, 4, 28, 28, 1)), jnp.float32
    )
    variables = model.init(jax.random.key(0), x_train, train=True)
    logits, updated = model.apply(
        variables, x_train, train=True, mutable=["batch_stats"]
    )
    assert logits.shape == (2, 4, 10)

    # Eval: no domain axis, runs on running stats, no state change needed.
    x_eval = x_train[1]
    logits_eval = model.apply(
        {"params": variables["params"], **updated}, x_eval, train=False
    )
    assert logits_eval.shape == (4, 10)
    assert np.all(np.isfinite(np.asarray(logits_eval)))


def test_lenet_eval_depends_only_on_target_branch_stats():
    """Perturbing SOURCE branch stats must not change eval output."""
    model = LeNetDWT(group_size=4)
    x_train = jnp.asarray(
        np.random.default_rng(4).normal(size=(2, 4, 28, 28, 1)), jnp.float32
    )
    variables = model.init(jax.random.key(0), x_train, train=True)
    _, updated = model.apply(
        variables, x_train, train=True, mutable=["batch_stats"]
    )
    params = variables["params"]
    stats = updated["batch_stats"]

    x_eval = x_train[0]
    base = model.apply({"params": params, "batch_stats": stats}, x_eval, train=False)

    # Perturb every branch-0 (source) stat leaf; eval must be invariant.
    poison = lambda a: a.at[0].add(jnp.asarray(7, a.dtype))
    poisoned = jax.tree.map(poison, stats)
    same = model.apply(
        {"params": params, "batch_stats": poisoned}, x_eval, train=False
    )
    np.testing.assert_array_equal(np.asarray(base), np.asarray(same))

    # Perturbing branch-1 (target) stats MUST change eval output.
    poisoned_t = jax.tree.map(lambda a: a.at[1].add(jnp.asarray(7, a.dtype)), stats)
    diff = model.apply(
        {"params": params, "batch_stats": poisoned_t}, x_eval, train=False
    )
    assert not np.allclose(np.asarray(base), np.asarray(diff))


def test_lenet_train_step_updates_all_branch_stats():
    model = LeNetDWT(group_size=4)
    rng = np.random.default_rng(5)
    # Source and target drawn from different distributions.
    x = jnp.asarray(
        np.stack([rng.normal(size=(4, 28, 28, 1)),
                  rng.normal(loc=2.0, size=(4, 28, 28, 1))]),
        jnp.float32,
    )
    variables = model.init(jax.random.key(0), x, train=True)
    _, updated = model.apply(
        variables, x, train=True, mutable=["batch_stats"]
    )
    before = variables["batch_stats"]
    after = updated["batch_stats"]
    changed = jax.tree.map(
        lambda a, b: np.any(np.asarray(a) != np.asarray(b)), before, after
    )
    assert all(jax.tree.leaves(changed))
